"""Pallas paged-attention decode kernel — gather-free reads of the KV page
pool (the vLLM paged-attention kernel shape, PAPERS.md).

The XLA paged path (``GPT2._paged_attn_inputs``) gathers ``pool[page_table]``
into a dense ``[b, H, max_seq, hd]`` view per layer per tick. On real chips
that round-trips the ENTIRE table width through HBM — gather read, dense
materialization write, attention read — every tick, which erases most of the
paged cache's bandwidth win (capacity still holds; traffic doesn't). This
kernel walks the page table directly instead:

- **One page per grid step.** The table rides as a SCALAR-PREFETCH operand
  (``pltpu.PrefetchScalarGridSpec``), so the K/V BlockSpec index maps read
  ``table[b, t]`` and Pallas DMAs exactly that physical page's rows into
  VMEM for grid step ``(b, kv_head, t)`` — the dense view is never
  materialized, and HBM traffic is proportional to the pages the table
  actually names (:func:`paged_hbm_bytes` is the analytic accounting
  the bench's A/B table uses).
- **In-kernel dequantize.** int4 pages unpack their nibbles (the shared
  ``pack_int4`` layout: channel halves contiguous) and both int4/int8 fold
  the per-row scales from ``quantize_kv_rows`` exactly where the XLA path
  does — key scales after the q·k dot, value scales into the probabilities
  before the p·v dot — so the math is the same sum in a different order.
- **Running (out, lse) merge.** Pages fold into online-softmax accumulators
  (running row-max, running denominator — the same logsumexp-merge shape as
  ``ops.ring_attention``'s hop merge), held in VMEM scratch across the
  page-walk grid dimension.
- **Dead-page skipping.** The batcher's sanitized table points every entry
  past a slot's live depth (and every dead slot's entire row) at the
  scratch page 0; pages whose first row is beyond every resident query's
  position skip compute via ``pl.when``, and the repeated scratch-page
  block index collapses to one resident copy — live work, not pool size,
  sets the bill.
- **GQA for free.** Query heads group over their kv head exactly like
  ``Llama._decode_attention``: the grid walks KV heads and each step's q
  block is that head's query GROUP (``rep × C`` rows), so one kernel serves
  GPT-2 (rep=1) and Llama (rep>1), dense-parity pinned for both.

Routing: ``DSML_PAGED_ATTN=pallas|xla`` (:func:`paged_attn_impl`; default
pallas on TPU, xla elsewhere — the gather path stays the fallback and the
parity oracle). All three paged serving surfaces (decode / chunked prefill /
speculative verify) route through here via ``_decode_core_paged``: their
masks are all ``key_pos <= query_pos``, which is the one mask this kernel
implements. On non-TPU backends the kernel runs under the Pallas
interpreter, which is how CI pins parity on the CPU mesh.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports on CPU builds too; guard anyway (ops/flash.py idiom)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "paged_attention",
    "paged_attn_impl",
    "paged_hbm_bytes",
]

_NEG_INF = -1e30
_MAX_FLOOR = -1e20  # running-max floor: exp() stays sane on fully-masked rows


def paged_attn_impl() -> str:
    """The paged-attention routing knob: ``DSML_PAGED_ATTN`` ∈
    {"pallas", "xla"}; unset/malformed defaults to the Pallas kernel on
    TPU and the XLA gather elsewhere (the kernel still RUNS off-TPU via
    the interpreter — tests opt in explicitly — but interpreted ticks are
    the wrong default for a CPU serving loop). Read at trace time: a
    batcher compiles its programs once, so flip the env before
    construction, not between ticks."""
    raw = os.environ.get("DSML_PAGED_ATTN", "").strip().lower()
    if raw in ("pallas", "xla"):
        return raw
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _vmem_spec(block_shape, index_map):
    if pltpu is not None:
        return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map)  # pragma: no cover


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.MemoryRef(shape, jnp.float32)  # pragma: no cover


def _kernel(table_ref, q_ref, pos_ref, k_ref, v_ref, *rest, mode, scale,
            page_size, n_pt, g_rows):
    """One (batch row, kv head, table entry) grid step: DMA'd page →
    dequantize → masked scores → online-softmax fold into the running
    (acc, m, l) scratch. ``rest`` is ``(k_s_ref, v_s_ref, o_ref, acc, m,
    l)`` for quantized pools and ``(o_ref, acc, m, l)`` for fp pages."""
    if mode:
        k_s_ref, v_s_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        k_s_ref, v_s_ref = None, None
        o_ref, acc, m_scr, l_scr = rest
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _MAX_FLOOR)
        l_scr[:] = jnp.zeros_like(l_scr)

    posq = pos_ref[0, 0].reshape(g_rows, 1)  # [G, 1] global query positions
    # pages whose FIRST row is past every resident query are fully masked
    # for this batch row — skip the compute (the sanitized table routes
    # them at the scratch page, whose repeated block index Pallas fetches
    # once; the skip is what keeps the MXU bill proportional to live rows)
    max_pos = jnp.max(posq)

    @pl.when(t * page_size <= max_pos)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
        kv_raw = k_ref[0, 0]
        if mode == "int4":
            hi = (kv_raw >> 4).astype(jnp.int8) - 8
            lo = (kv_raw & 0xF).astype(jnp.int8) - 8
            k = jnp.concatenate([hi, lo], axis=-1).astype(jnp.float32)
        else:
            k = kv_raw.astype(jnp.float32)  # int8 or fp rows
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, page]
        if mode:
            # per-row key scales fold AFTER the dot — identical math to the
            # XLA path's scores * k_s^T (scales are constant along hd)
            s = s * k_s_ref[0, 0].reshape(1, page_size)
        k_pos = t * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (g_rows, page_size), 1
        )
        s = jnp.where(k_pos <= posq, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * corr + jnp.sum(p, -1, keepdims=True), l_scr.shape
        )
        if mode == "int4":
            v_raw = v_ref[0, 0]
            hi = (v_raw >> 4).astype(jnp.int8) - 8
            lo = (v_raw & 0xF).astype(jnp.int8) - 8
            v = jnp.concatenate([hi, lo], axis=-1).astype(jnp.float32)
        else:
            v = v_ref[0, 0].astype(jnp.float32)
        if mode:
            # value scales fold into the probabilities BEFORE the p·v dot
            # (probs * v_s^T in the XLA path)
            p = p * v_s_ref[0, 0].reshape(1, page_size)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(t == n_pt - 1)
    def _finish():
        l_fin = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc[:] / l_fin).astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,
    pool_layer: dict,
    page_table: jax.Array,
    positions: jax.Array,
    mode: str | None,
    interpret: bool | None = None,
) -> jax.Array:
    """Decode attention straight off the page pool — no dense
    ``[b, H, S, hd]`` view.

    ``q`` [b, hq, C, hd] (C = 1 for decode, the window/chunk width for
    verify/prefill); ``pool_layer`` is ONE layer's pool entry dict
    (``k``/``v`` [P, hkv, page_size, ·] plus ``k_s``/``v_s`` [P, hkv,
    page_size, 1] when quantized — ``init_page_pool``'s layout);
    ``page_table`` [b, n_pt] int32 physical page per (slot, logical page)
    — the batcher's SANITIZED table (dead slots/entries at scratch page
    0); ``positions`` [b, C] int32 global positions of the query rows.
    The mask is ``key_pos <= query_pos`` — exactly the ``valid`` mask all
    three paged serving surfaces pass the XLA path. ``mode`` ∈ {None,
    "int8", "int4"} is the pool codec. Returns [b, hq, C, hd] in
    ``q.dtype``; numeric parity with the gather path and greedy-token
    bit-identity through the paged batcher are pinned in tests."""
    if mode not in (None, "int8", "int4"):
        raise ValueError(f"unknown page quant mode {mode!r}")
    b, hq, c, hd = q.shape
    n_pages, hkv, page_size, _ = pool_layer["k"].shape
    if hq % hkv:
        raise ValueError(f"query heads {hq} not grouped by kv heads {hkv}")
    n_pt = page_table.shape[1]
    rep = hq // hkv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # group query heads over their kv head (the GQA grouping rule — head
    # h serves kv head h // rep, matching Llama._decode_attention), then
    # flatten (rep, C) into one query-row axis: all of a kv head's queries
    # share its pages, so one grid step scores the whole group
    qg = q.reshape(b, hkv, rep, c, hd).reshape(b, hkv, rep * c, hd)
    posq = jnp.broadcast_to(
        jnp.asarray(positions, jnp.int32)[:, None, :], (b, rep, c)
    ).reshape(b, rep * c)
    g = rep * c
    gp = max(8, -(-g // 8) * 8)  # sublane-tileable query-row count
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
        # padded rows mask everything (-1 admits no key position); their
        # zero q rows produce finite garbage that is sliced off below
        posq = jnp.pad(posq, ((0, 0), (0, gp - g)), constant_values=-1)
    # positions ride VMEM broadcast over 8 sublanes (the flash lse trick:
    # the block shape stays Mosaic-tileable)
    pos8 = jnp.broadcast_to(posq[:, None, :], (b, 8, gp))

    kernel = functools.partial(
        _kernel, mode=mode, scale=hd ** -0.5, page_size=page_size,
        n_pt=n_pt, g_rows=gp,
    )
    in_specs = [
        _vmem_spec((1, 1, gp, hd), lambda bi, hi, ti, tab: (bi, hi, 0, 0)),
        _vmem_spec((1, 8, gp), lambda bi, hi, ti, tab: (bi, 0, 0)),
        # the page walk: table[b, t] names the physical page this grid
        # step reads — Pallas DMAs that page's rows, nothing else
        _vmem_spec((1, 1, page_size, pool_layer["k"].shape[-1]),
                   lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
        _vmem_spec((1, 1, page_size, pool_layer["v"].shape[-1]),
                   lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
    ]
    operands = [qg, pos8, pool_layer["k"], pool_layer["v"]]
    if mode:
        in_specs += [
            _vmem_spec((1, 1, page_size, 1),
                       lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
            _vmem_spec((1, 1, page_size, 1),
                       lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
        ]
        operands += [pool_layer["k_s"], pool_layer["v_s"]]

    if pltpu is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv, n_pt),
            in_specs=in_specs,
            out_specs=_vmem_spec((1, 1, gp, hd),
                                 lambda bi, hi, ti, tab: (bi, hi, 0, 0)),
            scratch_shapes=[
                _scratch((gp, hd)), _scratch((gp, 128)), _scratch((gp, 128)),
            ],
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hkv, gp, hd), jnp.float32),
            interpret=interpret,
        )(jnp.asarray(page_table, jnp.int32), *operands)
    else:  # pragma: no cover — pltpu always importable on supported builds
        raise RuntimeError("pallas TPU frontend unavailable")
    out = out[:, :, :g].reshape(b, hkv, rep, c, hd).reshape(b, hq, c, hd)
    return out.astype(q.dtype)


def paged_hbm_bytes(
    n_slots: int,
    n_pt: int,
    page_size: int,
    n_kv_head: int,
    head_dim: int,
    mode: str | None,
    live_pages: int,
    impl: str,
    n_query_rows: int = 1,
) -> int:
    """Analytic HBM bytes ONE layer's paged-attention read costs per
    decode tick — counted from the program structure, not sampled (the
    ``collectives.ring_wire_bytes`` contract), with the scratch-page
    term charged at its worst case. The bench's A/B table and the
    contract test's scales-with-live-work assertion both read this.

    ``impl="xla"`` — the gather path's bill is TABLE-shaped: it reads one
    page per table entry for every slot (``n_slots × n_pt`` pages, the
    scratch page re-read per duplicate entry), writes the gathered dense
    view, and reads that view back in the attention dots — regardless of
    how many rows are live. ``impl="pallas"`` — the kernel's bill is
    LIVE-shaped: ``live_pages`` counts live TABLE ENTRIES summed over
    slots (a CoW-shared page counts once per slot naming it — each
    (slot, head) grid row DMAs its own copy), each entry fetches once
    per kv head, and each slot's dead-entry tail re-fetches the scratch
    page once per (slot, head) run — the ``+ n_slots`` term (a slot with
    zero dead entries skips it; this model charges the worst case).
    Query/output bytes ride both and are counted for honesty; they are
    noise next to the pool traffic."""
    from dsml_tpu.ops.quantization import kv_row_bytes

    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown paged-attention impl {impl!r}")
    row = 2 * kv_row_bytes(head_dim, mode)  # one position's K + V (+scales)
    page_bytes = n_kv_head * page_size * row
    qo_bytes = 2 * n_slots * n_kv_head * n_query_rows * head_dim * 4
    if impl == "pallas":
        return (live_pages + n_slots) * page_bytes + qo_bytes
    gathered = n_slots * n_pt * page_bytes  # pool read, table-shaped
    # dense view materialized in the unpacked int8 (or fp) row width plus
    # scales, written once and read back by the attention dots
    dense_row = 2 * (head_dim + 4) if mode else 2 * 4 * head_dim
    dense = n_slots * n_pt * page_size * n_kv_head * dense_row
    return gathered + 2 * dense + qo_bytes
