"""Flash attention as a Pallas TPU kernel — the framework's hot-op kernel.

The reference has no on-device compute at all (its "GPUs" stream bytes,
``DSML/gpu_device_service/gpu_device_server.go:26-49``); its intended compute
API (vestigial ``RunForward``/``RunBackward`` RPCs, SURVEY.md §8.9) is
realized in this framework as jitted XLA graphs — and, for the attention hot
op, as a hand-written Pallas kernel so the [seq, seq] score matrix never
touches HBM:

- forward: blockwise q·kᵀ on the MXU with online-softmax accumulators
  (running row-max, running denominator) held in VMEM scratch across the
  innermost kv-block grid dimension;
- backward: the standard two-kernel flash split — one pass accumulates dq
  over kv blocks, a second accumulates dk/dv over q blocks — recomputing
  p = exp(s − L) from the forward's saved logsumexp rather than storing
  probabilities.

Causal blocks entirely above the diagonal are skipped via ``pl.when``
predication. On non-TPU backends the same kernels run under the Pallas
interpreter (``interpret=True``), which is how tests/test_flash.py validates
them on the CI CPU mesh; on TPU they compile through Mosaic.

Used by ``dsml_tpu.models.gpt2`` via ``attn_impl="flash"``; composes with
tensor parallelism (heads are already TP-sharded when this runs under
``shard_map``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_MAX_FLOOR = -1e20  # running-max floor: keeps exp() sane for fully-masked rows


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _vmem_spec(block_shape, index_map):
    if pltpu is not None:
        return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map)


def _pick_block(seq: int, preferred: int) -> int | None:
    for b in (preferred, 128, 64, 32, 16, 8):
        if b <= preferred and seq % b == 0:
            return b
    return None


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *, scale, causal, block_q, block_k, kv_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _MAX_FLOOR)
        l_scr[:] = jnp.zeros_like(l_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(l_scr[:, :1] * corr + jnp.sum(p, -1, keepdims=True), l_scr.shape)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    if causal:
        # blocks strictly above the diagonal contribute nothing
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == kv_blocks - 1)
    def _finish():
        l_fin = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc[:] / l_fin).astype(o_ref.dtype)
        # lse is stored [bh, 8, seq] — 8 identical sublanes keep the block
        # shape Mosaic-tileable (last two dims (8, block_q))
        lse_ref[0] = jnp.broadcast_to((m_scr[:, :1] + jnp.log(l_fin)).reshape(1, block_q), (8, block_q))


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    scale = d**-0.5
    q_blocks, kv_blocks = s_q // block_q, s_kv // block_k

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_blocks=kv_blocks,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, s_q), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.MemoryRef(shape, jnp.float32)  # pragma: no cover


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc, *, scale, causal, block_q, block_k, kv_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc[:] = acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == kv_blocks - 1)
    def _finish():
        dq_ref[0] = (acc[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q, block_k, q_blocks):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        # q blocks entirely above this kv block see none of it
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == q_blocks - 1)
    def _finish():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    scale = d**-0.5
    q_blocks, kv_blocks = s_q // block_q, s_kv // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [bh, s_q]
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, s_q))  # sublane-aligned like lse

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_blocks=kv_blocks,
        ),
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
            _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, q_blocks=q_blocks,
        ),
        grid=(bh, kv_blocks, q_blocks),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            _vmem_spec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            _vmem_spec((1, 8, block_q), lambda b, ki, qi: (b, 0, qi)),
            _vmem_spec((1, 8, block_q), lambda b, ki, qi: (b, 0, qi)),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, causal, block_q, block_k, interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention. Shapes: [batch, heads, seq, head_dim].

    Numerically equivalent to ``dsml_tpu.ops.attention.attention`` (tests
    assert it) but never materializes the [seq, seq] score matrix — peak
    memory is O(block_q · block_k) per core instead of O(seq²) per head.
    Falls back to the plain fused-XLA path when the sequence doesn't tile
    (block sizes must divide seq_q/seq_kv).
    """
    if q.ndim != 4:
        raise ValueError(f"expected [batch, heads, seq, head_dim], got {q.shape}")
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_kv, block_k)
    if bq is None or bk is None:
        from dsml_tpu.ops.attention import attention

        return attention(q, k, v, causal)
    if interpret is None:
        interpret = _interpret_default()

    def flat(t):
        return t.reshape(b * h, t.shape[2], d)

    out = _flash(flat(q), flat(k), flat(v), causal, bq, bk, interpret)
    return out.reshape(b, h, s_q, d)
