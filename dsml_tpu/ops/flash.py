"""Flash attention as Pallas TPU kernels — the framework's hot-op kernels.

The reference has no on-device compute at all (its "GPUs" stream bytes,
``DSML/gpu_device_service/gpu_device_server.go:26-49``); its intended compute
API (vestigial ``RunForward``/``RunBackward`` RPCs, SURVEY.md §8.9) is
realized in this framework as jitted XLA graphs — and, for the attention hot
op, as hand-written Pallas kernels so the [seq, seq] score matrix never
touches HBM:

- forward: blockwise q·kᵀ on the MXU with online-softmax accumulators
  (running row-max, running denominator) held in VMEM scratch across the
  innermost kv-block grid dimension; emits the per-row logsumexp.
- backward: the standard two-kernel flash split — one pass accumulates dq
  over kv blocks, a second accumulates dk/dv over q blocks — recomputing
  p = exp(s − L) from the forward's saved logsumexp rather than storing
  probabilities. The logsumexp output is differentiable too (its cotangent
  folds into ds as ``p · g_lse``), which is what lets whole flash calls be
  COMBINED downstream.
- :func:`ring_flash_attention` — sequence-parallel attention where every
  ring hop is one flash call: q/k blocks carry their global position
  offsets (SMEM scalars, so the causal mask is correct for any hop pair),
  K/V rotate via ``ppermute``, and the per-hop (out, lse) pairs merge with
  logsumexp weights. Exact full attention at O(block²) VMEM per chip —
  Ring Self-Attention (SURVEY.md §5.7) with a flash inner loop. For cp
  TRAINING prefer ``ops.ring_attention`` (``attn_impl="ring2"``): same
  merge math plus bidirectional streaming, causal hop skipping, and a
  backward that re-streams KV instead of letting autodiff save every
  visiting block (this one's residuals grow O(S) with ring size).
- :func:`flash_block_grads` — the raw one-block backward given MERGED
  (out, lse) statistics; the primitive that re-streaming backward calls.

Sequences that don't tile into blocks run through a PADDED path: zero-pad
to a block multiple (≤ 25% waste), mask the padded kv tail inside the
kernels via a ``kv_stop`` SMEM scalar, slice padded q rows off outputs —
cp/ring shards make odd residual lengths the common case.
``DSML_FLASH_BLOCK`` overrides the swept block defaults (docs/TUNING.md).

Causal blocks entirely above the diagonal are skipped via ``pl.when``
predication (a dynamic predicate when offsets are traced). On non-TPU
backends the same kernels run under the Pallas interpreter
(``interpret=True``), which is how tests validate them on the CI CPU mesh;
on TPU they compile through Mosaic.

Used by ``dsml_tpu.models.gpt2`` via ``attn_impl="flash"`` (single-chip) and
``attn_impl="ring_flash"`` (sequence-parallel).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from dsml_tpu.ops.collectives import ring_pass

try:  # pltpu is importable on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "flash_attention",
    "flash_attention_lse",
    "flash_block_grads",
    "flash_stream_hop",
    "ring_flash_attention",
]

_NEG_INF = -1e30
_MAX_FLOOR = -1e20  # running-max floor: keeps exp() sane for fully-masked rows


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _vmem_spec(block_shape, index_map):
    if pltpu is not None:
        return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map)


def _smem_spec():
    if pltpu is not None:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec()  # pragma: no cover


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.MemoryRef(shape, jnp.float32)  # pragma: no cover


def _pick_block(seq: int, preferred: int) -> int | None:
    # 512 in the fallback ladder matters since the auto default became 1024:
    # without it a kv length divisible by 512 but not 1024 (4608, 5632, ...)
    # would degrade straight to 256-wide blocks
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= preferred and seq % b == 0:
            return b
    return None


def _pad_choice(seq: int, preferred: int) -> tuple[int, int]:
    """(block, padded_len): exact ladder tiling when ``seq`` divides a ladder
    block (today's path, byte-identical); otherwise the largest ladder block
    whose zero-padding waste stays ≤ 25% of the padded length (floor 8).
    Ring/cp shards make odd residual lengths the COMMON case, and a
    sub-block pad — masked off via the kernels' kv_stop scalar — beats
    falling off the kernel onto the O(s²) XLA path."""
    b = _pick_block(seq, preferred)
    if b is not None:
        return b, seq
    for cand in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if cand > preferred:
            continue
        padded = -(-seq // cand) * cand
        if (padded - seq) * 4 <= padded:
            return cand, padded
    return 8, -(-seq // 8) * 8


def _env_block_override() -> tuple[int | None, int | None]:
    """``DSML_FLASH_BLOCK`` override for the auto block defaults: ``"B"``
    (both blocks) or ``"BQxBK"``. Lets cp-sharded (shorter per-rank)
    sequences be tuned without editing the kernel; explicit ``block_q``/
    ``block_k`` arguments still win. Malformed or non-multiple-of-8 values
    are ignored — a bad env var must degrade to the swept defaults, never
    crash a trace (docs/TUNING.md)."""
    raw = os.environ.get("DSML_FLASH_BLOCK", "").strip().lower()
    if not raw:
        return None, None
    try:
        if "x" in raw:
            q_s, k_s = raw.split("x", 1)
            bq, bk = int(q_s), int(k_s)
        else:
            bq = bk = int(raw)
    except ValueError:
        return None, None
    if bq < 8 or bk < 8 or bq % 8 or bk % 8:
        return None, None
    return bq, bk


def _default_blocks(
    s_q: int, s_kv: int, block_q: int | None, block_k: int | None,
    head_dim: int | None = None,
) -> tuple[int, int]:
    """Swept-on-hardware block defaults (scripts/flash_block_sweep.py on a
    v5e, k_extra=16 differenced timing, HEAD_DIM 64 — the GPT-2 shape): at
    sequence lengths >= 4096 the 1024x1024 tiling runs the fwd+bwd pair
    ~1.4x faster than 512x512 (43.7 vs 31.2 TFLOPs at seq 8192 — fewer
    grid revisits of the dq/dkv accumulators); anything wider than 1024
    already fails TPU compilation on VMEM at d=64. The 1024 widening is
    therefore GATED on head_dim <= 64: kernel VMEM scales with
    block x head_dim, so a d=128 model (Llama presets) at the same block
    could exhaust VMEM outright where the 512 default compiles — wider
    heads keep 512x512 until a sweep at that head_dim says otherwise.
    Below 4096 the 512x512 tiling measured best-or-equal wherever the
    differenced signal rose above tunnel jitter. Callers can still pin
    blocks explicitly (the ring path does, per-shard); lengths the
    preferred block doesn't divide degrade through _pick_block's ladder.

    ``DSML_FLASH_BLOCK`` ("B" or "BQxBK") overrides the swept auto defaults
    — the tuning knob for cp-sharded per-rank lengths the sweep never saw —
    but explicit arguments always win over the env."""
    env_q, env_k = _env_block_override()
    if block_q is None:
        block_q = env_q
    if block_k is None:
        block_k = env_k
    widen = head_dim is not None and head_dim <= 64
    if block_q is None:
        block_q = 1024 if (s_q >= 4096 and widen) else 512
    if block_k is None:
        block_k = 1024 if (s_kv >= 4096 and widen) else 512
    return block_q, block_k


def _positions(qs, ks, qi, ki, block_q, block_k):
    """Global (row, col) position grids for the current (q, kv) block pair."""
    q_pos = qs + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = ks + ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return q_pos, k_pos


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(qs_ref, ks_ref, kstop_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *, scale, causal, block_q, block_k, kv_blocks, mask_kv, qi=None, ki=None):
    # qi/ki may be pre-read grid indices: a wrapping kernel that delegates
    # here from inside pl.when must hoist its program_id reads to the top
    # level — interpret mode substitutes the primitive only when it's bound
    # in the outer kernel jaxpr, not inside a cond branch
    if qi is None:
        qi = pl.program_id(1)
    if ki is None:
        ki = pl.program_id(2)
    qs, ks = qs_ref[0], ks_ref[0]

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _MAX_FLOOR)
        l_scr[:] = jnp.zeros_like(l_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if mask_kv or causal:
            q_pos, k_pos = _positions(qs, ks, qi, ki, block_q, block_k)
            if mask_kv:
                # zero-padded kv tail (sequence not a block multiple): its
                # columns must not enter the softmax denominator
                s = jnp.where(k_pos < kstop_ref[0], s, _NEG_INF)
            if causal:
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(l_scr[:, :1] * corr + jnp.sum(p, -1, keepdims=True), l_scr.shape)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    if causal:
        # blocks with every column strictly in the future contribute nothing
        # (dynamic predicate: offsets are traced values)
        @pl.when(ks + ki * block_k <= qs + qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == kv_blocks - 1)
    def _finish():
        l_fin = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc[:] / l_fin).astype(o_ref.dtype)
        # lse is stored [bh, 8, seq] — 8 identical sublanes keep the block
        # shape Mosaic-tileable (last two dims (8, block_q))
        lse_ref[0] = jnp.broadcast_to((m_scr[:, :1] + jnp.log(l_fin)).reshape(1, block_q), (8, block_q))


def _flash_fwd(q, k, v, q_start, k_start, kv_stop, causal, block_q, block_k, interpret, mask_kv):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    scale = d**-0.5
    q_blocks, kv_blocks = s_q // block_q, s_kv // block_k

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_blocks=kv_blocks, mask_kv=mask_kv,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, s_q), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
        ],
        interpret=interpret,
    )(_scalar(q_start), _scalar(k_start), _scalar(kv_stop), q, k, v)
    return out, lse


def _scalar(x):
    return jnp.atleast_1d(jnp.asarray(x, jnp.int32))


# ---------------------------------------------------------------------------
# fused ring hop: flash forward + in-kernel KV streaming to the neighbor
# ---------------------------------------------------------------------------


def _stream_fwd_kernel(qs_ref, ks_ref, kstop_ref, pred_ref, nbr_ref,
                       q_ref, k_ref, v_ref, ksend_ref, vsend_ref,
                       o_ref, lse_ref, knext_ref, vnext_ref,
                       acc, m_scr, l_scr, send_sem, recv_sem, *,
                       scale, causal, block_q, block_k, q_blocks, kv_blocks,
                       n_bh, mask_kv, barrier):
    """:func:`_fwd_kernel` with the ring hop absorbed: at the FIRST grid
    step the resident KV shard starts a remote async copy into the
    neighbor's receive buffers (``pltpu.make_async_remote_copy``), the
    whole flash grid then computes while those bytes fly, and the LAST
    grid step waits both directions' semaphores — the MXU never idles on
    an XLA-visible ppermute between hops. ``pred_ref`` carries the causal
    hop-skip predicate INTO the kernel (a skipped pair writes the
    (0, lse-floor) identity the ring merge ignores) because the stream
    must run even when the math doesn't — every block tours the full
    ring regardless of masking. ``nbr_ref`` = (destination, source)
    logical device ids; the barrier handshake makes sure both neighbors'
    kernels (and so their receive buffers) exist before any send."""
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    def _rdma(i, src, dst):
        return pltpu.make_async_remote_copy(
            src, dst, send_sem.at[i], recv_sem.at[i],
            device_id=nbr_ref[0],
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    first = (b == 0) & (qi == 0) & (ki == 0)
    last = ((b == n_bh - 1) & (qi == q_blocks - 1) & (ki == kv_blocks - 1))

    @pl.when(first)
    def _send():
        if barrier:
            # both neighbors must have entered this collective before a
            # byte moves — their receive buffers are this kernel's outputs
            bsem = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(
                bsem, 1, device_id=nbr_ref[0],
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_signal(
                bsem, 1, device_id=nbr_ref[1],
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bsem, 2)
        _rdma(0, ksend_ref, knext_ref).start()
        _rdma(1, vsend_ref, vnext_ref).start()

    @pl.when(pred_ref[0] != 0)
    def _math():
        _fwd_kernel(qs_ref, ks_ref, kstop_ref, q_ref, k_ref, v_ref,
                    o_ref, lse_ref, acc, m_scr, l_scr, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    kv_blocks=kv_blocks, mask_kv=mask_kv, qi=qi, ki=ki)

    @pl.when((pred_ref[0] == 0) & (ki == kv_blocks - 1))
    def _masked():
        # the hop-skip identity: zero out, floored lse — exactly what the
        # unfused ring's lax.cond branch emits, so the merge math is
        # bit-identical between schedules
        o_ref[0] = jnp.zeros_like(o_ref[0])
        lse_ref[0] = jnp.full_like(lse_ref[0], -1e30)

    @pl.when(last)
    def _settle():
        _rdma(0, ksend_ref, knext_ref).wait()
        _rdma(1, vsend_ref, vnext_ref).wait()


def flash_stream_hop(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    pred,
    dst,
    src,
    causal: bool = True,
    q_start: jax.Array | int = 0,
    k_start: jax.Array | int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    collective_id: int = 7,
):
    """One FUSED ring-attention hop: flash attention of ``q`` against the
    resident ``k``/``v`` shard while that same shard streams to logical
    device ``dst`` inside the kernel's DMA pipeline. Returns
    ``(out, lse, k_next, v_next)`` — the attention pair for the merge plus
    the NEXT hop's residents, received from ``src`` (the opposite ring
    neighbor) into this call's output buffers.

    ``pred`` is the causal hop-skip predicate (traced bool): when false
    the kernel skips every score block and emits the ``(0, −1e30)`` merge
    identity, but the KV stream still runs — masked hops move bytes, not
    math, exactly like the unfused schedule's bare ppermute. The compute
    operands ride the padded-block path (odd shard lengths); the STREAMED
    buffers are the unpadded originals, so wire bytes match
    ``ring_kv_wire_bytes`` exactly.

    Logical device ids index ``jax.devices()`` order, which equals the
    ring rank only when the ring axis is the mesh's sole (or major-order
    equivalent) axis — ``ops.ring_attention`` only routes here under that
    condition (``DSML_RING_FUSED=dma``). Off-TPU the kernel runs under
    the Pallas interpreter, whose remote-copy emulation is how CI pins
    hop parity on the CPU mesh."""
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    block_q, block_k = _default_blocks(s_q, s_kv, block_q, block_k, d)
    bq, pq = _pad_choice(s_q, block_q)
    bk, pk = _pad_choice(s_kv, block_k)
    if interpret is None:
        interpret = _interpret_default()
    mask_kv = pk != s_kv
    qf, kf, vf = _flat3(q), _flat3(k), _flat3(v)
    ksend, vsend = kf, vf  # unpadded residents are what tours the ring
    if pq != s_q:
        qf = jnp.pad(qf, ((0, 0), (0, pq - s_q), (0, 0)))
    if mask_kv:
        kf = jnp.pad(kf, ((0, 0), (0, pk - s_kv), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pk - s_kv), (0, 0)))
    kv_stop = k_start + s_kv
    bh = qf.shape[0]
    q_blocks, kv_blocks = pq // bq, pk // bk
    kernel = functools.partial(
        _stream_fwd_kernel, scale=d ** -0.5, causal=causal, block_q=bq,
        block_k=bk, q_blocks=q_blocks, kv_blocks=kv_blocks, n_bh=bh,
        mask_kv=mask_kv, barrier=not interpret,
    )
    any_spec = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
    nbr = jnp.stack([jnp.asarray(dst, jnp.int32), jnp.asarray(src, jnp.int32)])
    pred_arr = jnp.atleast_1d(jnp.asarray(pred, jnp.int32))
    out, lse, k_next, v_next = pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            _smem_spec(), _smem_spec(), _smem_spec(),
            _smem_spec(), _smem_spec(),
            _vmem_spec((1, bq, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, bk, d), lambda b, qi, ki: (b, ki, 0)),
            _vmem_spec((1, bk, d), lambda b, qi, ki: (b, ki, 0)),
            any_spec, any_spec,
        ],
        out_specs=[
            _vmem_spec((1, bq, d), lambda b, qi, ki: (b, qi, 0)),
            _vmem_spec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
            any_spec, any_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, pq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, pq), jnp.float32),
            jax.ShapeDtypeStruct(ksend.shape, ksend.dtype),
            jax.ShapeDtypeStruct(vsend.shape, vsend.dtype),
        ],
        scratch_shapes=[
            _scratch((bq, d)), _scratch((bq, 128)), _scratch((bq, 128)),
            pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=collective_id,
        ) if not interpret else None,
        interpret=interpret,
    )(_scalar(q_start), _scalar(k_start), _scalar(kv_stop), pred_arr, nbr,
      qf, kf, vf, ksend, vsend)
    if pq != s_q:
        out = out[:, :s_q]
        lse = lse[:, :, :s_q]
    return (out.reshape(b, h, s_q, d), lse[:, 0, :].reshape(b, h, s_q),
            k_next.reshape(b, h, s_kv, d), v_next.reshape(b, h, s_kv, d))


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(qs_ref, ks_ref, kstop_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref, dq_ref, acc, *, scale, causal, block_q, block_k, kv_blocks, mask_kv):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    qs, ks = qs_ref[0], ks_ref[0]

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)
        glse = glse_ref[0, 0].reshape(block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if mask_kv or causal:
            q_pos, k_pos = _positions(qs, ks, qi, ki, block_q, block_k)
            if mask_kv:
                s = jnp.where(k_pos < kstop_ref[0], s, _NEG_INF)
            if causal:
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta + glse)  # glse: cotangent of the lse output
        acc[:] = acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(ks + ki * block_k <= qs + qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == kv_blocks - 1)
    def _finish():
        dq_ref[0] = (acc[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(qs_ref, ks_ref, kstop_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q, block_k, q_blocks, mask_kv):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    qs, ks = qs_ref[0], ks_ref[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)
        glse = glse_ref[0, 0].reshape(block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if mask_kv or causal:
            q_pos, k_pos = _positions(qs, ks, qi, ki, block_q, block_k)
            if mask_kv:
                s = jnp.where(k_pos < kstop_ref[0], s, _NEG_INF)
            if causal:
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta + glse)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        # q blocks entirely before this kv block see none of it
        @pl.when(qs + qi * block_q + block_q - 1 >= ks + ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == q_blocks - 1)
    def _finish():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse8, do, glse8, q_start, k_start, kv_stop, causal, block_q, block_k, interpret, mask_kv):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    scale = d**-0.5
    q_blocks, kv_blocks = s_q // block_q, s_kv // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [bh, s_q]
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, s_q))  # sublane-aligned like lse
    qrow = [
        _smem_spec(),
        _smem_spec(),
        _smem_spec(),
        _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        _vmem_spec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        _vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
        _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
        _vmem_spec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
    ]

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_blocks=kv_blocks, mask_kv=mask_kv,
        ),
        grid=(bh, q_blocks, kv_blocks),
        in_specs=qrow,
        out_specs=_vmem_spec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(_scalar(q_start), _scalar(k_start), _scalar(kv_stop), q, k, v, do, lse8, delta, glse8)

    krow = [
        _smem_spec(),
        _smem_spec(),
        _smem_spec(),
        _vmem_spec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
        _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        _vmem_spec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
        _vmem_spec((1, 8, block_q), lambda b, ki, qi: (b, 0, qi)),
        _vmem_spec((1, 8, block_q), lambda b, ki, qi: (b, 0, qi)),
        _vmem_spec((1, 8, block_q), lambda b, ki, qi: (b, 0, qi)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, q_blocks=q_blocks, mask_kv=mask_kv,
        ),
        grid=(bh, kv_blocks, q_blocks),
        in_specs=krow,
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            _vmem_spec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(_scalar(q_start), _scalar(k_start), _scalar(kv_stop), q, k, v, do, lse8, delta, glse8)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# differentiable core (out AND lse)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash(q, k, v, q_start, k_start, kv_stop, causal, block_q, block_k, interpret, mask_kv):
    out, lse8 = _flash_fwd(q, k, v, q_start, k_start, kv_stop, causal, block_q, block_k, interpret, mask_kv)
    return out, lse8[:, 0, :]


def _flash_fwd_rule(q, k, v, q_start, k_start, kv_stop, causal, block_q, block_k, interpret, mask_kv):
    out, lse8 = _flash_fwd(q, k, v, q_start, k_start, kv_stop, causal, block_q, block_k, interpret, mask_kv)
    return (out, lse8[:, 0, :]), (q, k, v, out, lse8, q_start, k_start, kv_stop)


def _flash_bwd_rule(causal, block_q, block_k, interpret, mask_kv, res, g):
    q, k, v, out, lse8, q_start, k_start, kv_stop = res
    g_out, g_lse = g
    bh, s_q, _ = q.shape
    glse8 = jnp.broadcast_to(g_lse.astype(jnp.float32)[:, None, :], (bh, 8, s_q))
    dq, dk, dv = _flash_bwd(
        q, k, v, out, lse8, g_out, glse8, q_start, k_start, kv_stop, causal,
        block_q, block_k, interpret, mask_kv
    )
    return dq, dk, dv, None, None, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _flat3(t):
    b, h, s, d = t.shape
    return t.reshape(b * h, s, d)


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_start: jax.Array | int = 0,
    k_start: jax.Array | int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Flash attention returning ``(out, lse)``. Shapes: q/k/v
    [batch, heads, seq, head_dim] → out same-as-q, lse [batch, heads, seq_q]
    (float32 logsumexp over the kv positions this call saw).

    ``q_start``/``k_start`` are the GLOBAL positions of the first q/k row
    (traced values allowed) — the causal mask compares global positions, so
    ring/sharded callers can run any (q-block, kv-block) pair. Both outputs
    are differentiable. ANY length runs through the kernel: lengths the
    block ladder can't tile exactly are zero-padded up to a block multiple
    (≤ 25% waste), with the padded kv tail masked off inside the kernels via
    a ``kv_stop`` SMEM scalar and padded q rows sliced away — cp/ring shards
    make odd residual lengths the common case, so the kernel rather than an
    XLA fallback must own them.
    """
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    block_q, block_k = _default_blocks(s_q, s_kv, block_q, block_k, d)
    bq, pq = _pad_choice(s_q, block_q)
    bk, pk = _pad_choice(s_kv, block_k)
    if interpret is None:
        interpret = _interpret_default()
    mask_kv = pk != s_kv
    qf, kf, vf = _flat3(q), _flat3(k), _flat3(v)
    if pq != s_q:
        # padded q rows are ZERO (s = 0·k exactly — no overflow risk in the
        # backward's p = exp(s − lse)) and sliced off below; the slice's
        # transpose zero-pads their cotangent, so autodiff needs no help
        qf = jnp.pad(qf, ((0, 0), (0, pq - s_q), (0, 0)))
    if mask_kv:
        kf = jnp.pad(kf, ((0, 0), (0, pk - s_kv), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pk - s_kv), (0, 0)))
    kv_stop = k_start + s_kv  # global position the REAL kv columns end at
    out, lse = _flash(qf, kf, vf, q_start, k_start, kv_stop, causal, bq, bk, interpret, mask_kv)
    if pq != s_q:
        out = out[:, :s_q]
        lse = lse[:, :s_q]
    return out.reshape(b, h, s_q, d), lse.reshape(b, h, s_q)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention. Shapes: [batch, heads, seq, head_dim].

    Numerically equivalent to ``dsml_tpu.ops.attention.attention`` (tests
    assert it) but never materializes the [seq, seq] score matrix — peak
    memory is O(block_q · block_k) per core instead of O(seq²) per head.
    Sequences that don't tile into blocks run through the kernel's padded
    path (zero-padded to a block multiple, kv tail masked via ``kv_stop``)
    rather than falling back to the O(s²) XLA graph.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [batch, heads, seq, head_dim], got {q.shape}")
    out, _ = flash_attention_lse(q, k, v, causal, 0, 0, block_q, block_k, interpret)
    return out


def flash_block_grads(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    do: jax.Array,
    g_lse: jax.Array | None = None,
    causal: bool = True,
    q_start: jax.Array | int = 0,
    k_start: jax.Array | int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Raw flash backward of ONE (q-shard, kv-block) pair given MERGED
    statistics — the primitive ring attention's own backward re-streams KV
    through (``ops.ring_attention``).

    ``out``/``lse`` are the TOTAL attention output and logsumexp over EVERY
    kv block (the ring's merged accumulators), so the kernels' recomputed
    ``p = exp(s − lse)`` are the globally-correct softmax rows and the
    returned ``(dq, dk, dv)`` are this block pair's exact contributions to
    the full-attention gradients — summing them over all kv blocks
    reproduces the single-call flash backward. No custom-vjp wrapper: the
    caller owns the accumulation (dq locally, dk/dv around the reverse
    ring). Handles untileable lengths through the same padded path as
    :func:`flash_attention_lse`.

    Shapes: q/out/do [b, h, s_q, hd], k/v [b, h, s_kv, hd], lse/g_lse
    [b, h, s_q] (``g_lse``: cotangent of the merged lse output, None = 0).
    Returns float32 (dq, dk, dv) with the unpadded input shapes.
    """
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    block_q, block_k = _default_blocks(s_q, s_kv, block_q, block_k, d)
    bq, pq = _pad_choice(s_q, block_q)
    bk, pk = _pad_choice(s_kv, block_k)
    if interpret is None:
        interpret = _interpret_default()
    mask_kv = pk != s_kv
    qf, of, dof = _flat3(q), _flat3(out), _flat3(do)
    kf, vf = _flat3(k), _flat3(v)
    lse_f = lse.reshape(b * h, s_q).astype(jnp.float32)
    glse_f = (
        jnp.zeros_like(lse_f) if g_lse is None
        else g_lse.reshape(b * h, s_q).astype(jnp.float32)
    )
    if pq != s_q:
        pad3 = ((0, 0), (0, pq - s_q), (0, 0))
        qf, of, dof = (jnp.pad(t, pad3) for t in (qf, of, dof))
        # padded q rows: q = 0 ⇒ s = 0 exactly and do = 0 ⇒ ds = 0, so a
        # zero-padded lse (p = exp(0 − 0) = 1) contributes nothing anywhere
        # a real gradient lands; their dq rows are sliced off below
        lse_f = jnp.pad(lse_f, ((0, 0), (0, pq - s_q)))
        glse_f = jnp.pad(glse_f, ((0, 0), (0, pq - s_q)))
    if mask_kv:
        pad3 = ((0, 0), (0, pk - s_kv), (0, 0))
        kf, vf = jnp.pad(kf, pad3), jnp.pad(vf, pad3)
    lse8 = jnp.broadcast_to(lse_f[:, None, :], (b * h, 8, pq))
    glse8 = jnp.broadcast_to(glse_f[:, None, :], (b * h, 8, pq))
    dq, dk, dv = _flash_bwd(
        qf, kf, vf, of, lse8, dof, glse8, q_start, k_start, k_start + s_kv,
        causal, bq, bk, interpret, mask_kv,
    )
    dq = dq[:, :s_q].astype(jnp.float32).reshape(b, h, s_q, d)
    dk = dk[:, :s_kv].astype(jnp.float32).reshape(b, h, s_kv, d)
    dv = dv[:, :s_kv].astype(jnp.float32).reshape(b, h, s_kv, d)
    return dq, dk, dv


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """Ring attention with a flash kernel per hop (call under ``shard_map``).

    Each rank holds a sequence shard [batch, heads, seq/n, head_dim]; K/V
    rotate ``n−1`` hops around the ring. Every hop is ONE
    :func:`flash_attention_lse` call whose global offsets make the causal
    mask exact for that (q-shard, kv-shard) pair; the per-hop (out, lse)
    pairs then merge with logsumexp weights:

        lse_tot = logsumexp_i(lse_i);  out = Σᵢ exp(lse_i − lse_tot)·out_i

    which reconstructs exact full attention (hops that are entirely masked
    contribute lse ≈ −∞ → weight 0). Scores never exceed
    O(block_q·block_k) on any chip. Gradients flow through the kernels'
    custom VJP (including the lse term). Falls back to the XLA ring
    (``ops.attention.ring_attention``) when the shard doesn't tile.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return flash_attention(q, k, v, causal, block_q, block_k)
    seq_block = q.shape[-2]
    # per-SHARD kv length decides the block defaults (each hop's flash call
    # sees one shard of K/V)
    block_q, block_k = _default_blocks(seq_block, seq_block, block_q, block_k,
                                       q.shape[-1])
    if _pick_block(seq_block, block_q) is None or _pick_block(seq_block, block_k) is None:
        from dsml_tpu.ops.attention import ring_attention

        return ring_attention(q, k, v, axis_name, causal)
    rank = lax.axis_index(axis_name)

    # Online merge (same shape as ops.attention.ring_attention's fold): only
    # ONE running (out, lse) pair is alive — stacking all n hops would hold
    # the full sequence in f32 on every chip, defeating the point of SP.
    run_out = None
    run_lse = None
    kv = (k, v)
    for hop in range(n):
        k_off = (rank - hop) % n  # whose K/V block is resident this hop
        o, l = flash_attention_lse(
            q, kv[0], kv[1], causal,
            q_start=rank * seq_block, k_start=k_off * seq_block,
            block_q=block_q, block_k=block_k,
        )
        o = o.astype(jnp.float32)
        if run_out is None:
            run_out, run_lse = o, l
        else:
            new_lse = jnp.logaddexp(run_lse, l)
            w_prev = jnp.exp(run_lse - new_lse)[..., None]
            w_new = jnp.exp(l - new_lse)[..., None]
            run_out = w_prev * run_out + w_new * o
            run_lse = new_lse
        if hop != n - 1:
            kv = ring_pass(kv, axis_name, +1)

    return run_out.astype(q.dtype)
