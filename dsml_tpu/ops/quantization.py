"""Block-scaled quantization + compressed collective schedules.

The reference's memory/communication literature (ActNN/GACT activation
compression, SURVEY.md §2.4 folder 7; gradient-compression systems in folder
6) realized TPU-first:

- :func:`quantize_int8` / :func:`dequantize_int8` — blockwise absmax-scaled
  int8 with *stochastic* rounding (unbiased: E[q·scale] = x), so compressed
  gradients don't bias SGD. On TPU the quantizer is a Pallas kernel using
  the on-core PRNG (``pltpu.prng_random_bits``) per the TPU kernel playbook;
  elsewhere an XLA path with ``jax.random`` does the same math.
- :func:`compressed_all_reduce` — the v1 compressed sync: each rank
  quantizes its contribution, int8 blocks + f32 scales all-gather, every
  rank dequantizes and reduces locally. O(n) wire bytes per rank — kept as
  the latency-optimal small-payload shape and the A/B baseline.
- :func:`quantized_ring_all_reduce` — the v2 schedule (EQuARX-style,
  PAPERS.md): block-scaled int8 **or int4** quantization *inside* the
  2(n−1)-step ring. Scatter-reduce hops quantize the outgoing chunk,
  dequantize-accumulate at the receiver, re-quantize for the next hop; the
  all-gather half circulates each owner's quantized representation
  UNCHANGED (one quantization per reduced segment — no per-hop error
  compounding, and every rank dequantizes the same bytes, so the
  all-reduce postcondition holds bit-exactly across ranks). Bandwidth-
  optimal volume at 8/4 bits per element instead of v1's
  gather-everything; ``bidirectional=True`` is the full-duplex ring2.
- :func:`quantized_flat_reduce_scatter` — the same quantized scatter-reduce
  half standalone, with ``flat_reduce_scatter``'s rank-i-gets-segment-i
  layout: the ZeRO-2 bucket sync primitive.
- :func:`quantize_roundtrip` / error feedback — deterministic-rounding
  compression round trip; ``parallel.bucketing`` folds the residual
  ``x − roundtrip(x)`` into the next step's gradients so repeated
  quantized syncs don't drift (EF-SGD).
- :func:`compressed_checkpoint` — ActNN-style compressed rematerialization:
  ``jax.checkpoint`` whose stash is the int8-quantized input activation, so
  the per-layer residual footprint drops ~4× below even plain remat.

``dsml_tpu.parallel.dp`` exposes the gradient paths as ``algorithm="q8"``
(v1) and ``"q8_ring" / "q8_ring2" / "q4_ring" / "q4_ring2" / "quant"``
(v2; ``"quant"`` resolves per dtype from ``DSML_QUANT`` — see
:func:`quant_algorithm_for`). ``GPT2Config.remat = "int8"`` selects the
activation path; the GPT-2 int4 KV cache shares :func:`pack_int4` /
:func:`unpack_int4`.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "QuantizedTensor",
    "QuantScheme",
    "QuantizedWeight",
    "get_scheme",
    "default_qblock",
    "quant_algorithm_for",
    "weight_quant_mode",
    "quantize_weight_blocks",
    "dequantize_weight_blocks",
    "quantized_matmul",
    "pack_int4",
    "unpack_int4",
    "quantize_kv_rows",
    "dequantize_kv_rows",
    "kv_row_bytes",
    "quantize_int8",
    "dequantize_int8",
    "quantize_roundtrip",
    "quantized_ring_all_reduce",
    "quantized_flat_reduce_scatter",
    "quantized_ring_wire_bytes",
    "compressed_all_reduce",
    "compressed_checkpoint",
]

_BLOCK = 512  # elements per scale block


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Blockwise int8 tensor. A pytree whose array children are (values,
    scales) and whose size/shape/dtype ride as STATIC aux data — so it can
    cross jit/custom_vjp boundaries (e.g. as a ``compressed_checkpoint``
    residual) without the metadata leaking into the trace."""

    values: jax.Array  # int8, [blocks, _BLOCK]
    scales: jax.Array  # f32, [blocks, 1]
    size: int  # original element count (static)
    shape: tuple  # original shape (static)
    dtype: object  # original dtype (static)

    def tree_flatten(self):
        return (self.values, self.scales), (self.size, self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def _blocked(x: jax.Array):
    flat = x.astype(jnp.float32).reshape(-1)
    size = flat.shape[0]
    padded = -(-size // _BLOCK) * _BLOCK
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    return flat.reshape(-1, _BLOCK), size


def _quantize_xla(blocks: jax.Array, key: jax.Array):
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / 127.0, 1e-12)
    y = blocks / scales
    # stochastic rounding: floor(y + u), u ~ U[0,1) — unbiased for any y
    u = jax.random.uniform(key, blocks.shape, jnp.float32)
    q = jnp.clip(jnp.floor(y + u), -127, 127).astype(jnp.int8)
    return q, scales


def _quantize_pallas(blocks: jax.Array, seed: jax.Array):
    """TPU path: one Pallas program per 8-row block strip, on-core PRNG."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = blocks.shape[0]
    strip = 8  # f32 sublane tile
    padded_rows = -(-rows // strip) * strip
    if padded_rows != rows:
        blocks = jnp.pad(blocks, ((0, padded_rows - rows), (0, 0)))

    def kernel(seed_ref, x_ref, q_ref, s_ref):
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        x = x_ref[:]
        scale = jnp.maximum(jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0, 1e-12)
        y = x / scale
        bits = pltpu.bitcast(pltpu.prng_random_bits(y.shape), jnp.uint32)
        # u in [0,1) from the top 24 bits; floor(y+u) = unbiased round.
        # (bitcast the shifted bits to int32 — values < 2^24 so sign-safe;
        # Mosaic has no direct uint32→f32 cast)
        u = pltpu.bitcast(bits >> 8, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
        q_ref[:] = jnp.clip(jnp.floor(y + u), -127, 127).astype(jnp.int8)
        s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)

    # no interpret fallback: the Pallas interpreter has no rules for the TPU
    # PRNG primitives — callers route non-TPU backends to the XLA path
    q, s = pl.pallas_call(
        kernel,
        grid=(padded_rows // strip,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((strip, _BLOCK), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((strip, _BLOCK), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((strip, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded_rows, _BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((padded_rows, 128), jnp.float32),
        ],
    )(jnp.atleast_1d(seed).astype(jnp.int32), blocks)
    return q[:rows], s[:rows, :1]


def quantize_int8(x: jax.Array, seed: jax.Array | int = 0, use_pallas: bool | None = None) -> QuantizedTensor:
    """Blockwise (512-element) absmax int8 quantization, stochastically
    rounded. ``seed`` varies the rounding noise (pass the training step)."""
    blocks, size = _blocked(x)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        q, s = _quantize_pallas(blocks, jnp.asarray(seed, jnp.int32))
    else:
        key = jax.random.PRNGKey(jnp.asarray(seed, jnp.int32))
        q, s = _quantize_xla(blocks, key)
    return QuantizedTensor(q, s, size, tuple(x.shape), x.dtype)


def dequantize_int8(qt: QuantizedTensor) -> jax.Array:
    flat = (qt.values.astype(jnp.float32) * qt.scales).reshape(-1)[: qt.size]
    return flat.reshape(qt.shape).astype(qt.dtype)


# ---------------------------------------------------------------------------
# Quant schemes (int8 / int4), env knobs, shared nibble packing
# ---------------------------------------------------------------------------

_SCHEME_TABLE = {"int8": (8, 127), "int4": (4, 7)}


@dataclasses.dataclass(frozen=True)
class QuantScheme:
    """Static description of one block-scaled integer format: ``bits`` on
    the wire per element, symmetric range ``[-qmax, qmax]``, one f32 scale
    per ``block`` elements. int4 packs two values per byte
    (:func:`pack_int4`), so its block must be even."""

    name: str  # "int8" | "int4"
    bits: int
    qmax: int
    block: int

    @property
    def wire_bytes_per_block(self) -> int:
        """Bytes one quantized block occupies on the wire: packed values
        plus its f32 scale."""
        return self.block * self.bits // 8 + 4


def default_qblock() -> int:
    """Elements per scale block: 512 (the v1 ``quantize_int8`` block, kept —
    docs/TUNING.md), overridable via ``DSML_QBLOCK``. Malformed, non-positive
    or odd values fall back (odd blocks would split an int4 nibble pair)."""
    try:
        b = int(os.environ.get("DSML_QBLOCK", _BLOCK))
    except ValueError:
        return _BLOCK
    return b if b > 0 and b % 2 == 0 else _BLOCK


def get_scheme(name: str, block: int | None = None) -> QuantScheme:
    """Resolve ``"int8"``/``"int4"`` (or a :class:`QuantScheme`, returned
    as-is) to a scheme with ``block`` elements per scale (default:
    :func:`default_qblock`)."""
    if isinstance(name, QuantScheme):
        return name
    if name not in _SCHEME_TABLE:
        raise ValueError(
            f"unknown quant scheme {name!r}; choose from {sorted(_SCHEME_TABLE)}"
        )
    bits, qmax = _SCHEME_TABLE[name]
    block = default_qblock() if block is None else int(block)
    if block <= 0 or block % 2:
        raise ValueError(f"quant block must be positive and even, got {block}")
    return QuantScheme(name, bits, qmax, block)


_ALGO_FOR_SCHEME = {
    ("int8", "ring"): "q8_ring",
    ("int8", "ring2"): "q8_ring2",
    ("int4", "ring"): "q4_ring",
    ("int4", "ring2"): "q4_ring2",
}
# the sweep-chosen default (docs/TUNING.md § Quantized collectives): int8
# keeps the loss trajectory within tolerance without error feedback being
# mandatory, ring2 rides full-duplex ICI at half the per-direction payload
_DEFAULT_QUANT = "int8:ring2"


def quant_algorithm_for(dtype) -> str:
    """The ``DSML_QUANT`` env knob: which quantized sync a given gradient
    dtype should use when the caller says ``algorithm="quant"``.

    Grammar: ``SCHEME[:ALGO]`` applied to every float dtype, or a per-dtype
    comma list ``float32=int8:ring2,bfloat16=int4:ring2`` (unlisted dtypes
    fall back to the ``default=`` entry, else the built-in default).
    SCHEME ∈ {int8, int4, none}; ALGO ∈ {ring, ring2} (default ring2).
    ``none`` means sync that dtype unquantized (the fp32 ring). Malformed
    values fall back to the default rather than failing a training step.
    """
    key = str(jnp.dtype(dtype)) if not isinstance(dtype, str) else dtype
    raw = os.environ.get("DSML_QUANT", "").strip() or _DEFAULT_QUANT
    chosen = None
    if "=" in raw:
        table = {}
        for item in raw.split(","):
            if "=" in item:
                k, _, v = item.partition("=")
                table[k.strip()] = v.strip()
        chosen = table.get(key, table.get("default"))
    else:
        chosen = raw
    if not chosen:
        chosen = _DEFAULT_QUANT
    scheme, _, algo = chosen.partition(":")
    scheme, algo = scheme.strip(), (algo.strip() or "ring2")
    if scheme == "none":
        return algo if algo in ("ring", "ring2") else "ring"
    if scheme not in _SCHEME_TABLE or algo not in ("ring", "ring2"):
        scheme, _, algo = _DEFAULT_QUANT.partition(":")
    return _ALGO_FOR_SCHEME[(scheme, algo)]


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int values in ``[-7, 7]`` two-per-byte along the last axis
    (must be even): offset to ``q+8`` ∈ [1, 15], contiguous HALVES — the
    first half of the axis rides the high nibbles, the second half the low
    — so the unpack is a concat of two shift/mask ops, never an
    interleaving gather. This is THE nibble layout: the GPT-2 int4 KV
    cache and the int4 collective wire format both use it (bit-identity
    to the original KV-cache packing pinned in tests)."""
    if q.shape[-1] % 2:
        raise ValueError(f"pack_int4 needs an even last axis, got {q.shape}")
    q = q.astype(jnp.int32) + 8
    half = q.shape[-1] // 2
    return (q[..., :half] << 4 | q[..., half:]).astype(jnp.uint8)


def unpack_int4(p: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: ``[..., k]`` packed bytes →
    ``[..., 2k]`` int8 in ``[-7, 7]`` (channel halves contiguous)."""
    hi = (p >> 4).astype(jnp.int8) - 8
    lo = (p & 0xF).astype(jnp.int8) - 8
    return jnp.concatenate([hi, lo], axis=-1)


def quantize_kv_rows(x: jax.Array, mode: str = "int4"):
    """Symmetric absmax quantization of KV rows ``[..., rows, hd]`` →
    ``(values, f32 scales [..., rows, 1])`` — one scale PER ROW (a row is
    one token position's K or V vector), so a row quantizes independently
    of every other row in its page: cache/page writes never touch other
    positions' scales, and the bytes are identical whether the rows live
    in a dense ``[b, h, max_seq, hd]`` cache or a paged ``[pages, h,
    page_size, hd]`` pool (the page-table gather parity the paged KV
    cache rests on). ``mode="int8"`` stores int8 values directly;
    ``"int4"`` packs two offset nibbles per byte (:func:`pack_int4` —
    even ``hd`` required). THE one KV codec: the GPT-2/Llama dense
    quantized cache and the serving page pool both quantize through
    here (bit-identity pinned in tests)."""
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown KV quant mode {mode!r}; choose 'int8' or 'int4'")
    x32 = x.astype(jnp.float32)
    a = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    if mode == "int4":
        if x.shape[-1] % 2:
            raise ValueError(
                f"int4 KV rows need an even trailing dim, got {x.shape}"
            )
        s = jnp.where(a > 0, a / 7.0, 1.0)
        return pack_int4(jnp.clip(jnp.round(x32 / s), -7, 7)), s
    s = jnp.where(a > 0, a / 127.0, 1.0)
    return jnp.round(x32 / s).astype(jnp.int8), s


def dequantize_kv_rows(values: jax.Array, scales: jax.Array,
                       mode: str = "int4") -> jax.Array:
    """Inverse of :func:`quantize_kv_rows` → f32 rows ``[..., rows, hd]``.
    The serving hot path never calls this (attention feeds the int8
    values into its dots and folds the scales after — see
    ``GPT2._cache_attn_inputs``); it exists for codec round-trip tests
    and host-side tooling that wants the dequantized rows."""
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown KV quant mode {mode!r}; choose 'int8' or 'int4'")
    q = unpack_int4(values) if mode == "int4" else values
    return q.astype(jnp.float32) * scales


def kv_row_bytes(head_dim: int, mode: str | None) -> int:
    """HBM bytes one K or V row (one position, one head) costs under
    ``mode`` (None = f32), scale included — the analytic accounting the
    paged-KV capacity bench and docs/TUNING.md sizing rules use."""
    if mode is None:
        return 4 * head_dim
    if mode == "int8":
        return head_dim + 4  # int8 values + one f32 scale
    if mode == "int4":
        if head_dim % 2:
            raise ValueError(f"int4 KV rows need an even head_dim, got {head_dim}")
        return head_dim // 2 + 4  # two nibbles per byte + one f32 scale
    raise ValueError(f"unknown KV quant mode {mode!r}")


# ---------------------------------------------------------------------------
# Blocked weight quantization + the dequant-fused decode matmul
# ---------------------------------------------------------------------------
# Decode is weight-HBM-bandwidth-bound: the matmul's cost is reading the
# weight, not the FLOPs. The w8a16 per-channel path (models.common.
# quantize_weights_int8) already halves/quarters the bytes and lets XLA fuse
# the convert into the read; this section is the KERNEL form of the same
# idea — weights live in HBM as int8 or nibble-packed int4 with one f32
# scale per (k-block, output channel), and a Pallas matmul unpacks the
# integers INSIDE VMEM and folds the scale AFTER each per-block dot
# (sum_k x·q is integer-exact in f32; one multiply per block per channel
# recovers the dequantized partial sum). The full-width weight never exists
# outside a VMEM tile, at 4x (int8) / 8x (int4) HBM compression vs f32.


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedWeight:
    """A block-quantized matmul weight contracting on its FIRST axis.

    ``qw`` holds the integer codes over the PADDED 2-D form ``[d_p, n_p]``
    (int8) or ``[d_p // 2, n_p]`` (int4: each k-block's two row-halves
    packed hi/lo per byte — :func:`pack_int4`'s halves convention applied
    along the contraction axis, so the in-kernel unpack is two shift/mask
    ops and a concat, never a gather). ``qs`` is one f32 scale per
    (k-block, output channel): ``[d_p // block, n_p]``. The ORIGINAL shape
    and dtype ride as static aux so the tensor crosses jit boundaries and
    ``jax.tree`` maps like any param leaf."""

    qw: jax.Array  # int8 [d_p, n_p] | uint8 [d_p//2, n_p]
    qs: jax.Array  # f32 [d_p // block, n_p]
    scheme: str  # "int8" | "int4" (static)
    block: int  # k elements per scale block (static)
    shape: tuple  # original weight shape, first axis = contraction (static)
    dtype: object  # original dtype (static)

    def tree_flatten(self):
        return (self.qw, self.qs), (self.scheme, self.block, self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def hbm_bytes(self) -> int:
        """Resident compressed bytes: packed codes + scales."""
        return int(self.qw.nbytes + self.qs.nbytes)

    @property
    def dense_bytes(self) -> int:
        """What the SAME weight would cost dense at its original dtype —
        the compression-ratio denominator the bench row reports."""
        import numpy as np

        n = 1
        for s in self.shape:
            n *= int(s)
        return n * jnp.dtype(self.dtype).itemsize if n else 0


def weight_quant_mode() -> str | None:
    """The serving weight-codec knob: ``DSML_WEIGHT_QUANT`` ∈ {unset/"0"/
    "off"/"none" (full-precision weights), "int8"/"8", "int4"/"4"}.
    Malformed values degrade to off — a bad env var must never refuse to
    serve. Read once per batcher construction (docs/TUNING.md § Kernel
    fusion)."""
    raw = os.environ.get("DSML_WEIGHT_QUANT", "").strip().lower()
    if raw in ("int8", "8"):
        return "int8"
    if raw in ("int4", "4"):
        return "int4"
    return None


def _weight_pads(d: int, n: int, block: int) -> tuple[int, int, int]:
    """(kb, d_p, n_p): the effective k-block and padded operand dims. The
    contraction axis pads only to the 8-row sublane (≤ 7 wasted rows) and
    ``kb`` is the LARGEST multiple-of-8 divisor of that padded length not
    exceeding the scheme block — never a round-up to a full block, which
    would pad a 768-deep projection to 1024 and eat a third of the
    compression the codec exists to buy. Real model dims (768, 3072,
    4096 …) land on kb ∈ {384, 512} with zero waste; channels pad to the
    128-lane width (zero columns, scale 1 — exact zeros)."""
    d_p = -(-d // 8) * 8
    cap = min(int(block), d_p)
    kb = max(k for k in range(8, cap + 1, 8) if d_p % k == 0)
    n_p = -(-n // 128) * 128
    return kb, d_p, n_p


def quantize_weight_blocks(w: jax.Array, scheme="int8",
                           block: int | None = None) -> QuantizedWeight:
    """Block-quantize a matmul weight for the dequant-fused kernel:
    deterministic round-to-nearest, symmetric absmax per (k-block, output
    channel). ``w``'s FIRST axis is the contraction axis; trailing axes
    flatten into output channels (GPT-2's fused ``wqkv [d, 3, d]`` keeps a
    scale per (block, slot, channel) exactly like the per-channel path).
    Zero blocks take scale 1.0 so padding quantizes to exact zeros — pad
    rows contribute nothing to any dot."""
    sch = get_scheme(scheme, block)
    if w.ndim < 2:
        raise ValueError(f"weight quant needs a matmul weight, got shape {w.shape}")
    d = int(w.shape[0])
    orig_shape = tuple(int(s) for s in w.shape)
    wf = w.astype(jnp.float32).reshape(d, -1)
    n = int(wf.shape[1])
    kb, d_p, n_p = _weight_pads(d, n, sch.block)
    if (d_p, n_p) != (d, n):
        wf = jnp.pad(wf, ((0, d_p - d), (0, n_p - n)))
    nb = d_p // kb
    blocks = wf.reshape(nb, kb, n_p)
    a = jnp.max(jnp.abs(blocks), axis=1)  # [nb, n_p]
    qs = jnp.where(a > 0, a / sch.qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(blocks / qs[:, None, :]), -sch.qmax, sch.qmax)
    if sch.bits == 4:
        half = kb // 2
        hi = q[:, :half].astype(jnp.int32) + 8
        lo = q[:, half:].astype(jnp.int32) + 8
        qw = (hi << 4 | lo).astype(jnp.uint8).reshape(d_p // 2, n_p)
    else:
        qw = q.astype(jnp.int8).reshape(d_p, n_p)
    return QuantizedWeight(qw, qs, sch.name, kb, orig_shape, w.dtype)


def _unpack_weight_block(raw: jax.Array, int4: bool) -> jax.Array:
    """One VMEM weight tile → f32 codes: int4 tiles hold a k-block's two
    row-halves per byte (hi nibbles = rows [0, kb/2), lo = [kb/2, kb)) —
    THE same float sequence the reference dequantization commits to, so
    kernel and oracle agree exactly on int-representable values."""
    if int4:
        hi = (raw >> 4).astype(jnp.int8) - 8
        lo = (raw & 0xF).astype(jnp.int8) - 8
        return jnp.concatenate([hi, lo], axis=0).astype(jnp.float32)
    return raw.astype(jnp.float32)


def dequantize_weight_blocks(qwt: QuantizedWeight) -> jax.Array:
    """Reference inverse → f32 at the ORIGINAL shape. The serving hot path
    never calls this on-device (that would materialize the full-width
    weight in HBM — exactly what the fused kernel exists to avoid); it is
    the parity oracle and the XLA fallback's operand."""
    nb, n_p = qwt.qs.shape
    kb = qwt.block
    if qwt.scheme == "int4":
        raw = qwt.qw.reshape(nb, kb // 2, n_p)
        hi = (raw >> 4).astype(jnp.int8) - 8
        lo = (raw & 0xF).astype(jnp.int8) - 8
        q = jnp.concatenate([hi, lo], axis=1).astype(jnp.float32)
    else:
        q = qwt.qw.reshape(nb, kb, n_p).astype(jnp.float32)
    full = (q * qwt.qs[:, None, :]).reshape(nb * kb, n_p)
    d = qwt.shape[0]
    n = 1
    for s in qwt.shape[1:]:
        n *= int(s)
    return full[:d, :n].reshape(qwt.shape)


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc, *, nb, int4):
    """Grid (m tiles, n tiles, k blocks), k innermost: each step unpacks
    one weight tile in VMEM, takes the integer-code dot, and folds the
    per-(block, channel) scale AFTER the dot — one multiply per partial
    sum instead of one per weight element."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    w = _unpack_weight_block(w_ref[:], int4)
    part = jax.lax.dot_general(
        x_ref[:].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    acc[:] = acc[:] + part * s_ref[:]

    @pl.when(ki == nb - 1)
    def _flush():
        o_ref[:] = acc[:]


def quantized_matmul_vmem_bytes(bm: int, kb: int, bn: int, int4: bool) -> int:
    """Analytic VMEM working set of one fused-matmul grid step, at the
    Mosaic-padded footprint, with Pallas' automatic double buffering on
    every streamed operand (×2) — the guard the kernel route checks
    before committing to a block shape (docs/TUNING.md § Kernel fusion)."""
    from dsml_tpu.ops.vmem_budget import vmem_block_bytes

    x_b = vmem_block_bytes((bm, kb), 4)
    w_b = vmem_block_bytes((kb // 2, bn) if int4 else (kb, bn), 1)
    s_b = vmem_block_bytes((1, bn), 4)
    o_b = vmem_block_bytes((bm, bn), 4)
    acc = vmem_block_bytes((bm, bn), 4)
    return 2 * (x_b + w_b + s_b + o_b) + acc


def quantized_matmul(x: jax.Array, qwt: QuantizedWeight,
                     interpret: bool | None = None) -> jax.Array:
    """``x [m, d] @ dequant(qwt) → f32 [m, n]`` with the dequantization
    fused into the matmul: integer codes stream HBM→VMEM at their packed
    width, unpack + scale-fold happen per VMEM tile. Off-TPU the kernel
    runs under the Pallas interpreter (same float sequence — the CPU
    parity pin); a block shape that would blow the VMEM budget falls back
    to the XLA dequantize-then-dot path with a warn-once (the fallback
    DOES materialize the f32 weight — slower and bigger, but it serves)."""
    from dsml_tpu.ops.vmem_budget import fits_vmem, warn_once

    m, d = x.shape
    nb, n_p = qwt.qs.shape
    kb = qwt.block
    d_p = nb * kb
    int4 = qwt.scheme == "int4"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bm = -(-m // 8) * 8
    if bm > 128:
        bm = 128
    m_p = -(-m // bm) * bm
    bn = 128
    if not fits_vmem(quantized_matmul_vmem_bytes(bm, kb, bn, int4)):
        warn_once(
            f"qmm-vmem-{bm}-{kb}-{bn}-{qwt.scheme}",
            f"dequant-fused matmul block ({bm}x{kb}x{bn}, {qwt.scheme}) "
            f"exceeds the VMEM budget; falling back to the XLA "
            f"dequantize-then-dot path (set DSML_VMEM_LIMIT_MB or shrink "
            f"DSML_QBLOCK)",
        )
        return x.astype(jnp.float32) @ dequantize_weight_blocks(
            qwt
        ).reshape(d, -1)
    xf = x.astype(jnp.float32)
    if (m_p, d_p) != (m, d):
        xf = jnp.pad(xf, ((0, m_p - m), (0, d_p - d)))
    grid = (m_p // bm, n_p // bn, nb)
    kernel = functools.partial(_qmm_kernel, nb=nb, int4=int4)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, kb), lambda mi, ni, ki: (mi, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kb // 2 if int4 else kb, bn),
                         lambda mi, ni, ki: (ki, ni),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (ki, ni),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_p, n_p), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(xf, qwt.qw, qwt.qs)
    n = 1
    for s in qwt.shape[1:]:
        n *= int(s)
    return out[:m, :n]


def _block_quant(blocks: jax.Array, scheme: QuantScheme, seed=None):
    """Blockwise absmax quantization of ``[rows, block]`` f32. ``seed=None``
    = deterministic round-to-nearest (the error-feedback pairing: the
    residual exactly accounts the committed rounding); a seed = stochastic
    ``floor(y + u)`` (unbiased — the no-EF pairing, where zero-mean noise
    is what prevents step-correlated bias)."""
    scales = jnp.maximum(
        jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / scheme.qmax, 1e-12
    )
    y = blocks / scales
    if seed is None:
        q = jnp.round(y)
    else:
        u = jax.random.uniform(
            jax.random.PRNGKey(jnp.asarray(seed, jnp.int32)), blocks.shape, jnp.float32
        )
        q = jnp.floor(y + u)
    return jnp.clip(q, -scheme.qmax, scheme.qmax).astype(jnp.int8), scales


def _to_wire(q: jax.Array, scheme: QuantScheme) -> jax.Array:
    return pack_int4(q) if scheme.bits == 4 else q


def _from_wire(w: jax.Array, scheme: QuantScheme) -> jax.Array:
    return unpack_int4(w) if scheme.bits == 4 else w


def quantize_roundtrip(flat: jax.Array, scheme="int8") -> jax.Array:
    """``dequantize(quantize(flat))`` under deterministic rounding — the
    local compression a rank commits when it first ships ``flat``. The
    error-feedback residual is ``flat − quantize_roundtrip(flat)``: the
    dominant, locally-attributable term of the ring's compression error
    (later hops re-quantize *mixed* partial sums, which no single rank can
    account — the residual is a first-order correction, and the bench
    parity rows are what pin that it suffices)."""
    sch = get_scheme(scheme)
    flat = flat.astype(jnp.float32).reshape(-1)
    size = flat.shape[0]
    padded = -(-size // sch.block) * sch.block
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    q, scales = _block_quant(flat.reshape(-1, sch.block), sch)
    return (q.astype(jnp.float32) * scales).reshape(-1)[:size]


def quantized_ring_wire_bytes(
    n_elems: int, n_ranks: int, scheme="int8", bidirectional: bool = False
) -> int:
    """Analytic per-rank wire bytes of one quantized ring all-reduce:
    2(n−1) hops, each shipping one padded segment's packed values + f32
    block scales. The counterpart fp32 number is
    ``ops.collectives.ring_wire_bytes`` — their ratio is the bench grid's
    ``*_wire_reduction`` row (static shapes ⇒ exact, not sampled)."""
    sch = get_scheme(scheme)
    if n_ranks <= 1:
        return 0
    k = 2 if bidirectional else 1
    quantum = k * n_ranks * sch.block
    padded = -(-n_elems // quantum) * quantum
    blocks_per_seg = padded // (k * n_ranks) // sch.block
    per_hop = blocks_per_seg * sch.wire_bytes_per_block
    return k * 2 * (n_ranks - 1) * per_hop


def compressed_gather_wire_bytes(n_elems: int, n_ranks: int) -> int:
    """Analytic per-rank wire bytes of the v1 ``compressed_all_reduce``
    gather exchange: every rank receives the other n−1 ranks' full int8
    payload + f32 block scales — O(n) per rank, the wire-byte shape the
    ring schedules exist to beat."""
    if n_ranks <= 1:
        return 0
    blocks = -(-n_elems // _BLOCK)
    return (n_ranks - 1) * (blocks * _BLOCK + blocks * 4)


def _ring_perms(n: int) -> dict:
    # one definition of the ring neighborhood for every ring schedule
    from dsml_tpu.ops.collectives import ring_perm_tables

    return ring_perm_tables(n)


def _dither_seed(blocks: jax.Array, base, rank, salt: int) -> jax.Array:
    """Stochastic-rounding seed for one hop's chunk: the chunk's own bits
    (varies per step with the data) mixed with the caller seed, rank, and
    hop salt so no two ranks/hops share a dither pattern. ONE definition —
    the all-reduce and reduce-scatter schedules must never drift apart."""
    return (
        jnp.sum(lax.bitcast_convert_type(blocks, jnp.int32), dtype=jnp.int32)
        + base
        + rank * jnp.int32(7919)
        + jnp.int32(salt)
    )


def _quant_chunk_wire(blocks, scheme: QuantScheme, stochastic, base, rank, salt):
    """``[rows, block]`` f32 → (wire values, scales): the one quantize-
    for-the-wire step both ring schedules ship each hop through."""
    if stochastic:
        q, sc = _block_quant(blocks, scheme, seed=_dither_seed(blocks, base, rank, salt))
    else:
        q, sc = _block_quant(blocks, scheme)
    return _to_wire(q, scheme), sc


def _dequant_wire(wire, sc, scheme: QuantScheme) -> jax.Array:
    """Inverse of :func:`_quant_chunk_wire`, flattened to 1-D."""
    return (_from_wire(wire, scheme).astype(jnp.float32) * sc).reshape(-1)


def quantized_ring_all_reduce(
    x: jax.Array,
    axis_name: str,
    scheme="int8",
    bidirectional: bool = False,
    mean: bool = True,
    stochastic: bool = True,
    seed: jax.Array | int = 0,
) -> jax.Array:
    """Block-scaled quantized ring all-reduce (SUM/AVG), inside
    ``shard_map``.

    The 2(n−1)-step ring schedule of ``ops.collectives`` with quantization
    *inside* it (EQuARX, PAPERS.md): every scatter-reduce hop quantizes its
    outgoing chunk to ``scheme`` (int8 or packed int4 + one f32 scale per
    block), the receiver dequantizes and accumulates in f32, and the next
    hop re-quantizes the partial sum. The all-gather half quantizes each
    fully-reduced segment ONCE (by its owner) and circulates the wire
    representation unchanged — no per-hop error compounding, and since
    every rank dequantizes the owner's exact bytes the result is
    bit-identical across ranks (the all-reduce postcondition, pinned in
    tests). ``bidirectional=True`` splits the payload into two halves
    running opposite directions (the ring2 full-duplex shape).

    Wire bytes: ~2(n−1)/n · ``bits``/8 per element (+4/block for scales)
    vs the fp32 ring's 2(n−1)/n · 4 — ≈4× (int8) / ≈8× (int4) fewer.

    ``stochastic=True`` (default) dithers each hop's rounding with a seed
    folded from the chunk's own bits + rank + hop, so slowly-moving
    coordinates don't see the same rounding direction every step;
    ``stochastic=False`` is deterministic round-to-nearest — the ERROR
    FEEDBACK pairing (the residual then accounts the committed error
    exactly, and resume is trivially bit-reproducible).

    Zero-padding up to a multiple of ``directions·n·block`` keeps hop
    boundaries block-aligned: pad lanes quantize to exactly 0 (absmax
    scaling maps 0 → 0 under both roundings), only ever combine with other
    ranks' pad lanes, and are sliced off before return — the no-leak
    property the odd-tail regression test pins."""
    sch = get_scheme(scheme)
    if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
        raise ValueError(
            f"quantized ring all-reduce needs a float input, got {jnp.result_type(x)}"
        )
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    signs = (+1, -1) if bidirectional else (+1,)
    k = len(signs)
    flat = x.astype(jnp.float32).reshape(-1)
    size = flat.shape[0]
    quantum = k * n * sch.block
    padded = -(-size // quantum) * quantum
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    part = padded // k
    seg = part // n
    rows = seg // sch.block
    rank = lax.axis_index(axis_name)
    perms = _ring_perms(n)
    base = jnp.asarray(seed, jnp.int32) * jnp.int32(1_000_003)

    def q_chunk(chunk, salt):
        # data-dependent dither, decorrelated across ranks AND hops
        return _quant_chunk_wire(
            chunk.reshape(rows, sch.block), sch, stochastic, base, rank, salt
        )

    def dq(wire, sc):
        return _dequant_wire(wire, sc, sch)

    parts = []
    for d, s in enumerate(signs):
        buf = flat[d * part : (d + 1) * part].reshape(n, seg)
        # Scatter-reduce: quantize → ship → dequantize-accumulate, per hop.
        for step in range(n - 1):
            send_idx = (rank - s * step) % n
            recv_idx = (rank - s * (step + 1)) % n
            chunk = lax.dynamic_index_in_dim(buf, send_idx, 0, keepdims=False)
            wire, sc = q_chunk(chunk, salt=2 * step + (s < 0))
            wire = lax.ppermute(wire, axis_name, perms[s])
            sc = lax.ppermute(sc, axis_name, perms[s])
            resident = lax.dynamic_index_in_dim(buf, recv_idx, 0, keepdims=False)
            buf = lax.dynamic_update_index_in_dim(
                buf, resident + dq(wire, sc), recv_idx, 0
            )
        # All-gather: the owner quantizes its reduced segment ONCE; hops
        # forward the received wire bytes untouched, so segment i is the
        # same dequantization everywhere (incl. on the owner itself, which
        # replaces its f32 copy with its own round trip).
        own_idx = (rank + s) % n
        carry_w, carry_s = q_chunk(
            lax.dynamic_index_in_dim(buf, own_idx, 0, keepdims=False),
            salt=1_000 + (s < 0),
        )
        out = lax.dynamic_update_index_in_dim(buf, dq(carry_w, carry_s), own_idx, 0)
        for step in range(n - 1):
            carry_w = lax.ppermute(carry_w, axis_name, perms[s])
            carry_s = lax.ppermute(carry_s, axis_name, perms[s])
            recv_idx = (rank - s * step) % n
            out = lax.dynamic_update_index_in_dim(out, dq(carry_w, carry_s), recv_idx, 0)
        parts.append(out.reshape(-1))
    full = parts[0] if k == 1 else jnp.concatenate(parts)
    full = full[:size]
    if mean:
        full = full / n
    return full.reshape(orig_shape).astype(orig_dtype)


def quantized_flat_reduce_scatter(
    flat: jax.Array,
    axis_name: str,
    scheme="int8",
    mean: bool = True,
    stochastic: bool = True,
    seed: jax.Array | int = 0,
) -> tuple[jax.Array, int]:
    """Quantized ring reduce-scatter of a flat vector: the scatter-reduce
    half of :func:`quantized_ring_all_reduce` alone, with
    ``ops.collectives.flat_reduce_scatter``'s layout contract — rank i is
    left with contiguous segment i of the (mean) reduction, f32, and
    ``padded`` is the length rounded up to a multiple of the axis size
    (NOT of the block: segments block-pad per hop internally, so the shard
    length matches the unquantized path's and ZeRO-2's sharded optimizer
    state keeps its exact shapes). The ZeRO-2 bucket primitive: (n−1) hops
    at ``bits``/8 bytes per element instead of fp32."""
    sch = get_scheme(scheme)
    if not jnp.issubdtype(jnp.result_type(flat), jnp.floating):
        raise ValueError(
            f"quantized reduce-scatter needs a float input, got {jnp.result_type(flat)}"
        )
    n = lax.axis_size(axis_name)
    x = flat.astype(jnp.float32).reshape(-1)
    size = x.shape[0]
    padded = -(-size // n) * n
    if padded != size:
        x = jnp.pad(x, (0, padded - size))
    if n == 1:
        return x, padded
    seg = padded // n
    rows = -(-seg // sch.block)
    blockpad = rows * sch.block - seg
    buf = x.reshape(n, seg)
    rank = lax.axis_index(axis_name)
    perm = _ring_perms(n)[+1]
    base = jnp.asarray(seed, jnp.int32) * jnp.int32(1_000_003)
    # virtual rank r−1 runs the forward schedule, so ownership lands on
    # segment (vr+1) = r — flat_reduce_scatter's rank-i-gets-segment-i rule
    vr = (rank - 1) % n

    def q_chunk(chunk, salt):
        if blockpad:
            chunk = jnp.pad(chunk, (0, blockpad))
        return _quant_chunk_wire(
            chunk.reshape(rows, sch.block), sch, stochastic, base, rank, salt
        )

    def dq(wire, sc):
        return _dequant_wire(wire, sc, sch)[:seg]

    for step in range(n - 1):
        send_idx = (vr - step) % n
        recv_idx = (vr - step - 1) % n
        chunk = lax.dynamic_index_in_dim(buf, send_idx, 0, keepdims=False)
        wire, sc = q_chunk(chunk, salt=step)
        wire = lax.ppermute(wire, axis_name, perm)
        sc = lax.ppermute(sc, axis_name, perm)
        resident = lax.dynamic_index_in_dim(buf, recv_idx, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(buf, resident + dq(wire, sc), recv_idx, 0)
    shard = lax.dynamic_index_in_dim(buf, rank, 0, keepdims=False)
    if mean:
        shard = shard / n
    return shard, padded


def compressed_all_reduce(
    x: jax.Array, axis_name: str, seed: jax.Array | int = 0, mean: bool = True
) -> jax.Array:
    """8-bit all-reduce: quantize locally, all-gather int8 values + scales
    (≈4× fewer bytes on the wire than f32), dequantize-and-reduce locally.
    Call under ``shard_map``. Unbiased: stochastic rounding makes the
    expected result equal the exact (mean) reduction."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    # de-correlate rounding noise across ranks so errors average out
    rank_seed = jnp.asarray(seed, jnp.int32) * jnp.int32(1_000_003) + lax.axis_index(axis_name)
    qt = quantize_int8(x, rank_seed)
    vals = lax.all_gather(qt.values, axis_name)  # [n, blocks, B] int8
    scales = lax.all_gather(qt.scales, axis_name)  # [n, blocks, 1]
    total = jnp.sum(vals.astype(jnp.float32) * scales, axis=0)
    out = total.reshape(-1)[: qt.size].reshape(qt.shape)
    if mean:
        out = out / n
    return out.astype(x.dtype)


def compressed_checkpoint(fn, seed: jax.Array | int | None = None):
    """Compressed rematerialization (the reference's §7 Memory literature —
    ActNN `chen21z.pdf` / GACT `liu22v.pdf`, SURVEY.md §2.4): like
    ``jax.checkpoint``, the backward recomputes ``fn``'s internals instead of
    storing them — but where plain remat stashes the layer INPUT at full
    precision, this stashes it blockwise-int8 (4× smaller than f32, 2× than
    bf16), and the backward recomputes from the dequantized stash.

    ``fn(params, x) -> y`` with ``x`` a pytree of activations; float leaves
    are quantized, integer leaves (token ids) stashed exactly. ``params``
    ride in the residuals unquantized — they alias the live param buffers, so
    they cost no extra HBM. Gradients are those of ``fn`` evaluated at the
    dequantized input: exact in expectation (stochastic rounding is
    unbiased), approximation error bounded by the blockwise quantization
    noise — ActNN's accuracy argument. Safe under ``shard_map``: the
    backward's ``jax.vjp`` transposes any collectives inside ``fn`` the same
    way 1F1B's per-tick vjp does.

    ``seed=None`` (default) derives each leaf's rounding seed from the
    leaf's own bits, so the noise de-correlates across layers, microbatches,
    AND training steps with no step-counter plumbing — a fixed seed would
    make the rounding deterministic and turn the zero-mean noise into a
    step-correlated bias (the failure ``compressed_all_reduce`` avoids by
    per-rank seeds). Pass an explicit seed only for reproducibility studies.
    """

    def _q(leaf):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            if seed is None:
                # fold the activation's own bits into the seed: changes every
                # step/layer because the values do, costs one reduction over
                # a tensor already in registers. Sum the int32 BITCASTS, not
                # the floats: an f32 sum can saturate to inf/NaN on large
                # bf16 tensors, freezing the seed into a step-constant and
                # reintroducing the correlated-rounding bias; int32 addition
                # wraps, so the reduction is total and value-dependent
                leaf_seed = jnp.sum(
                    lax.bitcast_convert_type(leaf.astype(jnp.float32), jnp.int32)
                )
            else:
                leaf_seed = seed
            return quantize_int8(leaf, leaf_seed)
        return leaf

    def _dq(leaf):
        return dequantize_int8(leaf) if isinstance(leaf, QuantizedTensor) else leaf

    @jax.custom_vjp
    def wrapped(params, x):
        return fn(params, x)

    def fwd(params, x):
        return fn(params, x), (params, jax.tree.map(_q, x))

    def bwd(res, g):
        params, qx = res
        x_hat = jax.tree.map(_dq, qx, is_leaf=lambda l: isinstance(l, QuantizedTensor))
        _, vjp = jax.vjp(fn, params, x_hat)
        return vjp(g)

    wrapped.defvjp(fwd, bwd)
    return wrapped
