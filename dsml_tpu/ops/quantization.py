"""Int8 quantization with stochastic rounding + compressed gradient sync.

The reference's memory/communication literature (ActNN/GACT activation
compression, SURVEY.md §2.4 folder 7; gradient-compression systems in folder
6) realized TPU-first:

- :func:`quantize_int8` / :func:`dequantize_int8` — blockwise absmax-scaled
  int8 with *stochastic* rounding (unbiased: E[q·scale] = x), so compressed
  gradients don't bias SGD. On TPU the quantizer is a Pallas kernel using
  the on-core PRNG (``pltpu.prng_random_bits``) per the TPU kernel playbook;
  elsewhere an XLA path with ``jax.random`` does the same math.
- :func:`compressed_all_reduce` — gradient sync at 8 bits/element: each rank
  quantizes its contribution, int8 blocks + f32 scales all-gather (4×
  fewer wire bytes than f32), every rank dequantizes and reduces locally.
  Mean-preserving (AVG) by default, the DP gradient contract.
- :func:`compressed_checkpoint` — ActNN-style compressed rematerialization:
  ``jax.checkpoint`` whose stash is the int8-quantized input activation, so
  the per-layer residual footprint drops ~4× below even plain remat.

``dsml_tpu.parallel.dp`` exposes the gradient path as ``algorithm="q8"``;
``GPT2Config.remat = "int8"`` selects the activation path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "QuantizedTensor",
    "quantize_int8",
    "dequantize_int8",
    "compressed_all_reduce",
    "compressed_checkpoint",
]

_BLOCK = 512  # elements per scale block


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Blockwise int8 tensor. A pytree whose array children are (values,
    scales) and whose size/shape/dtype ride as STATIC aux data — so it can
    cross jit/custom_vjp boundaries (e.g. as a ``compressed_checkpoint``
    residual) without the metadata leaking into the trace."""

    values: jax.Array  # int8, [blocks, _BLOCK]
    scales: jax.Array  # f32, [blocks, 1]
    size: int  # original element count (static)
    shape: tuple  # original shape (static)
    dtype: object  # original dtype (static)

    def tree_flatten(self):
        return (self.values, self.scales), (self.size, self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def _blocked(x: jax.Array):
    flat = x.astype(jnp.float32).reshape(-1)
    size = flat.shape[0]
    padded = -(-size // _BLOCK) * _BLOCK
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    return flat.reshape(-1, _BLOCK), size


def _quantize_xla(blocks: jax.Array, key: jax.Array):
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / 127.0, 1e-12)
    y = blocks / scales
    # stochastic rounding: floor(y + u), u ~ U[0,1) — unbiased for any y
    u = jax.random.uniform(key, blocks.shape, jnp.float32)
    q = jnp.clip(jnp.floor(y + u), -127, 127).astype(jnp.int8)
    return q, scales


def _quantize_pallas(blocks: jax.Array, seed: jax.Array):
    """TPU path: one Pallas program per 8-row block strip, on-core PRNG."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = blocks.shape[0]
    strip = 8  # f32 sublane tile
    padded_rows = -(-rows // strip) * strip
    if padded_rows != rows:
        blocks = jnp.pad(blocks, ((0, padded_rows - rows), (0, 0)))

    def kernel(seed_ref, x_ref, q_ref, s_ref):
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        x = x_ref[:]
        scale = jnp.maximum(jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0, 1e-12)
        y = x / scale
        bits = pltpu.bitcast(pltpu.prng_random_bits(y.shape), jnp.uint32)
        # u in [0,1) from the top 24 bits; floor(y+u) = unbiased round.
        # (bitcast the shifted bits to int32 — values < 2^24 so sign-safe;
        # Mosaic has no direct uint32→f32 cast)
        u = pltpu.bitcast(bits >> 8, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
        q_ref[:] = jnp.clip(jnp.floor(y + u), -127, 127).astype(jnp.int8)
        s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)

    # no interpret fallback: the Pallas interpreter has no rules for the TPU
    # PRNG primitives — callers route non-TPU backends to the XLA path
    q, s = pl.pallas_call(
        kernel,
        grid=(padded_rows // strip,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((strip, _BLOCK), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((strip, _BLOCK), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((strip, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded_rows, _BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((padded_rows, 128), jnp.float32),
        ],
    )(jnp.atleast_1d(seed).astype(jnp.int32), blocks)
    return q[:rows], s[:rows, :1]


def quantize_int8(x: jax.Array, seed: jax.Array | int = 0, use_pallas: bool | None = None) -> QuantizedTensor:
    """Blockwise (512-element) absmax int8 quantization, stochastically
    rounded. ``seed`` varies the rounding noise (pass the training step)."""
    blocks, size = _blocked(x)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        q, s = _quantize_pallas(blocks, jnp.asarray(seed, jnp.int32))
    else:
        key = jax.random.PRNGKey(jnp.asarray(seed, jnp.int32))
        q, s = _quantize_xla(blocks, key)
    return QuantizedTensor(q, s, size, tuple(x.shape), x.dtype)


def dequantize_int8(qt: QuantizedTensor) -> jax.Array:
    flat = (qt.values.astype(jnp.float32) * qt.scales).reshape(-1)[: qt.size]
    return flat.reshape(qt.shape).astype(qt.dtype)


def compressed_all_reduce(
    x: jax.Array, axis_name: str, seed: jax.Array | int = 0, mean: bool = True
) -> jax.Array:
    """8-bit all-reduce: quantize locally, all-gather int8 values + scales
    (≈4× fewer bytes on the wire than f32), dequantize-and-reduce locally.
    Call under ``shard_map``. Unbiased: stochastic rounding makes the
    expected result equal the exact (mean) reduction."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    # de-correlate rounding noise across ranks so errors average out
    rank_seed = jnp.asarray(seed, jnp.int32) * jnp.int32(1_000_003) + lax.axis_index(axis_name)
    qt = quantize_int8(x, rank_seed)
    vals = lax.all_gather(qt.values, axis_name)  # [n, blocks, B] int8
    scales = lax.all_gather(qt.scales, axis_name)  # [n, blocks, 1]
    total = jnp.sum(vals.astype(jnp.float32) * scales, axis=0)
    out = total.reshape(-1)[: qt.size].reshape(qt.shape)
    if mean:
        out = out / n
    return out.astype(x.dtype)


def compressed_checkpoint(fn, seed: jax.Array | int | None = None):
    """Compressed rematerialization (the reference's §7 Memory literature —
    ActNN `chen21z.pdf` / GACT `liu22v.pdf`, SURVEY.md §2.4): like
    ``jax.checkpoint``, the backward recomputes ``fn``'s internals instead of
    storing them — but where plain remat stashes the layer INPUT at full
    precision, this stashes it blockwise-int8 (4× smaller than f32, 2× than
    bf16), and the backward recomputes from the dequantized stash.

    ``fn(params, x) -> y`` with ``x`` a pytree of activations; float leaves
    are quantized, integer leaves (token ids) stashed exactly. ``params``
    ride in the residuals unquantized — they alias the live param buffers, so
    they cost no extra HBM. Gradients are those of ``fn`` evaluated at the
    dequantized input: exact in expectation (stochastic rounding is
    unbiased), approximation error bounded by the blockwise quantization
    noise — ActNN's accuracy argument. Safe under ``shard_map``: the
    backward's ``jax.vjp`` transposes any collectives inside ``fn`` the same
    way 1F1B's per-tick vjp does.

    ``seed=None`` (default) derives each leaf's rounding seed from the
    leaf's own bits, so the noise de-correlates across layers, microbatches,
    AND training steps with no step-counter plumbing — a fixed seed would
    make the rounding deterministic and turn the zero-mean noise into a
    step-correlated bias (the failure ``compressed_all_reduce`` avoids by
    per-rank seeds). Pass an explicit seed only for reproducibility studies.
    """

    def _q(leaf):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            if seed is None:
                # fold the activation's own bits into the seed: changes every
                # step/layer because the values do, costs one reduction over
                # a tensor already in registers. Sum the int32 BITCASTS, not
                # the floats: an f32 sum can saturate to inf/NaN on large
                # bf16 tensors, freezing the seed into a step-constant and
                # reintroducing the correlated-rounding bias; int32 addition
                # wraps, so the reduction is total and value-dependent
                leaf_seed = jnp.sum(
                    lax.bitcast_convert_type(leaf.astype(jnp.float32), jnp.int32)
                )
            else:
                leaf_seed = seed
            return quantize_int8(leaf, leaf_seed)
        return leaf

    def _dq(leaf):
        return dequantize_int8(leaf) if isinstance(leaf, QuantizedTensor) else leaf

    @jax.custom_vjp
    def wrapped(params, x):
        return fn(params, x)

    def fwd(params, x):
        return fn(params, x), (params, jax.tree.map(_q, x))

    def bwd(res, g):
        params, qx = res
        x_hat = jax.tree.map(_dq, qx, is_leaf=lambda l: isinstance(l, QuantizedTensor))
        _, vjp = jax.vjp(fn, params, x_hat)
        return vjp(g)

    wrapped.defvjp(fwd, bwd)
    return wrapped
