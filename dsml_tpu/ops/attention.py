"""Attention ops: causal MHA, ring attention (SP), Ulysses all-to-all (CP).

Long-context scaling exists in the reference only as curated literature
(SURVEY.md §5.7): Ring Self-Attention (Li et al., ACL'23 — K/V blocks walk a
device ring) and LoongTrain's 2D attention (head-parallel × context-parallel
grids). Both are realized here as first-class mesh programs:

- :func:`attention` — plain fused softmax(QKᵀ)V with causal masking; XLA maps
  the batched matmuls straight onto the MXU.
- :func:`ring_attention` — sequence-parallel blockwise attention: each rank
  holds a sequence shard, K/V shards rotate ``n-1`` hops via ``ppermute``
  (the exact ring schedule the reference used for gradient bytes,
  ``gpu_coordinator_server.go:393-419``, lifted to attention blocks), with
  numerically-stable online-softmax accumulation so the result is exactly
  full attention.
- :func:`ulysses_attention` — all-to-all re-shard: sequence-sharded →
  head-sharded before attention, back after (DeepSpeed-Ulysses / LoongTrain
  head-parallelism), for meshes where an all-to-all beats n-1 ring hops.
- :func:`attention_2d` — LoongTrain's 2D grid: Ulysses all-to-all over the
  inner (fast) axis × ring over the outer (slow) axis.

All variants agree numerically; tests assert it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dsml_tpu.ops.collectives import ring_pass

__all__ = ["attention", "ring_attention", "ulysses_attention", "attention_2d"]

_NEG_INF = -1e30


def attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True) -> jax.Array:
    """Scaled dot-product attention. Shapes: [batch, heads, seq, head_dim]."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, _NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


def _block_scores(q, k, scale, causal, q_offset, k_offset, seq_block):
    """Scores for one (query-shard, key-shard) pair with global causal mask."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset * seq_block + jnp.arange(q.shape[-2])
        k_pos = k_offset * seq_block + jnp.arange(k.shape[-2])
        scores = jnp.where(q_pos[:, None] >= k_pos[None, :], scores, _NEG_INF)
    return scores


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str, causal: bool = True
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name``.

    Call under ``shard_map`` with q/k/v = this rank's sequence shard
    [batch, heads, seq/n, head_dim]. K/V rotate around the ring while each
    rank folds every visiting block into a running online-softmax
    accumulator (numerator, denominator, row-max) — attention never
    materializes the full [seq, seq] score matrix on any chip, which is what
    makes 100k+-token sequences fit (Ring Self-Attention; SURVEY.md §5.7).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return attention(q, k, v, causal)
    rank = lax.axis_index(axis_name)
    seq_block = q.shape[-2]
    scale = q.shape[-1] ** -0.5

    def fold(carry, kv_block, k_offset):
        num, den, row_max = carry
        k_blk, v_blk = kv_block
        scores = _block_scores(q, k_blk, scale, causal, rank, k_offset, seq_block)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        new_max = jnp.maximum(row_max, blk_max)
        # rescale previous accumulators to the new max, then add this block
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(scores - new_max)
        num = num * correction + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        den = den * correction + jnp.sum(p, axis=-1, keepdims=True)
        return (num, den, new_max)

    num = jnp.zeros_like(q)
    den = jnp.zeros(q.shape[:-1] + (1,), q.dtype)
    # floor at -1e20 (not -inf/-1e30): a fully-causal-masked block has
    # blk_max = -1e30, and an unfloored running max would make
    # exp(scores - max) = exp(0) = 1 for masked positions.
    row_max = jnp.full(q.shape[:-1] + (1,), -1e20, q.dtype)

    kv = (k, v)
    carry = (num, den, row_max)
    # n hops: fold the resident block, then rotate K/V to the next rank.
    for hop in range(n):
        k_offset = (rank - hop) % n  # whose K/V block is resident this hop
        carry = fold(carry, kv, k_offset)
        if hop != n - 1:
            kv = ring_pass(kv, axis_name, +1)
    num, den, _ = carry
    return num / jnp.maximum(den, 1e-30)


def _seq_to_heads(t, axis_name):  # [b, h, s/n, d] -> [b, h/n, s, d]
    return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)


def _heads_to_seq(t, axis_name):  # [b, h/n, s, d] -> [b, h, s/n, d]
    return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _check_head_split(q, n):
    if q.shape[1] % n:
        raise ValueError(f"heads ({q.shape[1]}) not divisible by axis size {n}")


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str, causal: bool = True,
    flash: bool = False,
) -> jax.Array:
    """Exact attention via all-to-all head/sequence re-sharding.

    Enter with sequence-sharded blocks [batch, heads, seq/n, head_dim];
    one all-to-all flips to head-sharded full sequences
    [batch, heads/n, seq, head_dim], attention runs locally, a second
    all-to-all flips back. Requires heads % axis_size == 0.

    ``flash=True`` runs the local attention through the Pallas flash kernel
    (``ops.flash``) — after the re-shard each rank holds the FULL sequence
    for its head group, so this is where the [seq, seq] score matrix would
    otherwise materialize; flash keeps it at O(block²) VMEM.
    """
    n = lax.axis_size(axis_name)
    if flash:
        from dsml_tpu.ops.flash import flash_attention as attn_fn
    else:
        attn_fn = attention
    if n == 1:
        return attn_fn(q, k, v, causal)
    _check_head_split(q, n)
    out = attn_fn(
        _seq_to_heads(q, axis_name), _seq_to_heads(k, axis_name), _seq_to_heads(v, axis_name), causal
    )
    return _heads_to_seq(out, axis_name)


def attention_2d(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    inner_axis: str,
    outer_axis: str,
    causal: bool = True,
    flash: bool = False,
) -> jax.Array:
    """LoongTrain-style 2D attention: head-parallel inner × context-parallel
    outer grid (SURVEY.md §5.7, ``Literatures/2.Sequence Parallelism/
    2406.18485v1.pdf``).

    The sequence is sharded over BOTH axes, outer-major — under ``shard_map``
    pass the sequence dim spec ``P((outer, inner))`` so rank (o, i) holds
    global sub-block ``o·n_inner + i``. One all-to-all over the *inner* axis
    (the fast interconnect — ICI intra-slice on TPU) re-shards heads and
    leaves every inner rank holding its group's full contiguous outer block;
    ring attention then walks K/V around the *outer* axis only (the slow
    hops — DCN inter-slice), so the n−1-step ring is n_inner× shorter than a
    flat ring over all devices. A second all-to-all restores the layout.

    Requires ``heads % inner_axis_size == 0``; exact for any causal/full mask.
    ``flash=True`` runs the outer ring with one Pallas flash call per hop
    (``ops.flash.ring_flash_attention``).
    """
    if flash:
        from dsml_tpu.ops.flash import ring_flash_attention

        ring_fn = ring_flash_attention
    else:
        ring_fn = ring_attention
    n_inner = lax.axis_size(inner_axis)
    if n_inner == 1:
        return ring_fn(q, k, v, outer_axis, causal)
    _check_head_split(q, n_inner)
    out = ring_fn(
        _seq_to_heads(q, inner_axis),
        _seq_to_heads(k, inner_axis),
        _seq_to_heads(v, inner_axis),
        outer_axis,
        causal,
    )
    return _heads_to_seq(out, inner_axis)
