"""VMEM budget guard for hand-scheduled Pallas kernels.

A kernel whose working set exceeds the chip's VMEM fails inside Mosaic with
an opaque allocation error at COMPILE time — long after the caller chose the
kernel path. Every hand-pipelined kernel in this tree (the double-buffered
paged-attention walk, the dequant-fused decode matmul) therefore sizes its
buffers HERE, at trace time, against the same model: blocks live in VMEM at
their Mosaic-padded footprint (last dim padded to the 128-lane width,
second-minor to the dtype's sublane tile), manual double buffering doubles
every streamed buffer, and Pallas' own automatic pipelining double-buffers
grid-walked BlockSpec operands. If the estimate doesn't fit, the caller
falls back to its XLA path (or the unpipelined kernel) with a WARN-ONCE —
a slower tick beats a crashed trace, and one log line beats a Mosaic
stack trace (docs/TUNING.md "Kernel fusion" has the sizing rule).

``DSML_VMEM_LIMIT_MB`` overrides the default 16 MiB/core budget (the v4/v5
figure the flash block sweep assumed); the guard spends at most
``_SPEND_FRACTION`` of it, leaving headroom for Mosaic's own spills,
semaphores, and the operands the estimate can't see.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("dsml_tpu.vmem")

__all__ = ["vmem_limit_bytes", "vmem_block_bytes", "fits_vmem", "warn_once"]

_DEFAULT_VMEM_BYTES = 16 * 1024 * 1024  # per-core VMEM on v4/v5-class chips
_SPEND_FRACTION = 0.9  # headroom for spills/semaphores the estimate omits

# sublane tile height per itemsize (the Mosaic (sublane, 128-lane) tiling:
# f32 packs (8, 128), bf16 (16, 128), int8/uint8 (32, 128))
_SUBLANE = {4: 8, 2: 16, 1: 32}

_warned: set = set()


def vmem_limit_bytes() -> int:
    """The per-core VMEM budget the guards size against. ``DSML_VMEM_LIMIT_MB``
    overrides (whole MiB; malformed/non-positive values fall back to the
    default — a bad env var must never crash a trace)."""
    raw = os.environ.get("DSML_VMEM_LIMIT_MB", "").strip()
    if raw:
        try:
            mb = int(raw)
            if mb > 0:
                return mb * 1024 * 1024
        except ValueError:
            pass
    return _DEFAULT_VMEM_BYTES


def vmem_block_bytes(shape, itemsize: int) -> int:
    """Mosaic-padded VMEM footprint of one buffer: the last dim pads to the
    128-lane width, the second-minor to the dtype's sublane tile, leading
    dims multiply through. 1-D shapes are treated as a single sublane row.
    This is why a (page, 1) f32 scale column costs a full 128-lane stripe —
    the padding is physical, so the budget must charge it."""
    dims = [int(d) for d in shape]
    if not dims:
        return itemsize
    sub = _SUBLANE.get(int(itemsize), 8)
    lanes = -(-dims[-1] // 128) * 128
    rows = -(-(dims[-2] if len(dims) >= 2 else 1) // sub) * sub
    lead = 1
    for d in dims[:-2]:
        lead *= d
    return lead * rows * lanes * itemsize


def fits_vmem(nbytes: int) -> bool:
    """True when ``nbytes`` of kernel working set fits the spendable slice
    of the VMEM budget."""
    return nbytes <= int(vmem_limit_bytes() * _SPEND_FRACTION)


def warn_once(key: str, msg: str) -> None:
    """Log ``msg`` once per process per ``key`` — the fallback path runs
    every tick, the explanation should not."""
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


def _reset_for_tests() -> None:  # pragma: no cover - test hook
    _warned.clear()
