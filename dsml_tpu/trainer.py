"""Data-parallel trainer: the reference's training loop, compiled.

Reproduces the observable behavior of the reference client's epoch loop
(``DSML/client/client.go:516-659``: batched SGD, per-epoch "Average Loss /
Accuracy" lines, final test accuracy) with the semantics it intended: the
global batch is sharded across the mesh's ``dp`` axis, gradients all-reduce
on-device, and forward/backward/update run as one donated jitted step.
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax
import jax.numpy  # noqa: F401 (used via jax.numpy.array in warm-start copy)
import numpy as np
import optax

from dsml_tpu.obs import GoodputTracker, StepBreakdown, get_registry
from dsml_tpu.obs import flight_recorder, hangwatch
from dsml_tpu.obs.memory import get_memory_ledger, maybe_dump_oom
from dsml_tpu.obs.sentinels import TrainingSentinels
from dsml_tpu.parallel.dp import make_dp_train_step, make_eval_step
from dsml_tpu.parallel.mesh import data_mesh
from dsml_tpu.utils.config import Config, field
from dsml_tpu.utils.data import Dataset, prefetch_batches, shard_batches
from dsml_tpu.utils.logging import get_logger
from dsml_tpu.utils.metrics import EpochMetrics, MetricsLogger, ProgressBar

log = get_logger("trainer")


@dataclasses.dataclass
class TrainConfig(Config):
    epochs: int = field(10, help="training epochs (reference: 10)")
    batch_size: int = field(64, help="GLOBAL batch size (reference: 64)")
    lr: float = field(0.01, help="SGD learning rate (reference: 0.01)")
    optimizer: str = field("sgd", help="sgd | momentum | adam | adamw")
    lr_schedule: str = field("constant", help="constant | cosine | linear | step | plateau (the adaptive LR the reference README promised but never shipped, SURVEY.md §8.8)")
    warmup_steps: int = field(0, help="linear warmup steps for the schedule")
    plateau_patience: int = field(5, help="plateau schedule: epochs-worth of steps without improvement before decaying")
    plateau_factor: float = field(0.5, help="plateau schedule: lr decay factor")
    algorithm: str = field("xla", help="gradient sync: xla | ring | ring2 | auto | naive | q8 (v1 int8 gather) | q8_ring | q8_ring2 | q4_ring | q4_ring2 (block-quantized ring schedules) | quant (per-dtype via DSML_QUANT)")
    error_feedback: bool = field(False, help="error-feedback residuals for quantized ring sync (q8_ring/q8_ring2/q4_ring/q4_ring2/quant): the per-rank compression error re-enters the next step's gradients; residuals are checkpointable state and ride resume bit-identically")
    bucket_mb: float = field(0.0, help="explicit-sync gradient bucket size in MiB (0 = the DSML_BUCKET_MB default, currently 4; negative = single buffer, the pre-bucketing A/B shape)")
    dp: int = field(0, help="data-parallel devices (0 = all local)")
    seed: int = field(0, help="init + shuffle seed")
    log_metrics: str = field("", help="optional JSONL metrics path")
    checkpoint_dir: str = field("", help="checkpoint directory ('' = no checkpointing; native sharded backend, docs/CHECKPOINT.md)")
    save_every: int = field(1, help="checkpoint every N epochs")
    save_every_steps: int = field(0, help="ALSO checkpoint every N steps mid-epoch (0 = epoch boundaries only); the data-loader position (epoch, consumed batches) rides the manifest so a preempted run resumes mid-epoch bit-identically; step-granularity saves use the global step as the checkpoint id")
    keep_checkpoints: int = field(3, help="max checkpoints retained (older steps garbage-collected)")
    resume: bool = field(False, help="resume from the latest checkpoint in checkpoint_dir")
    progress: bool = field(False, help="draw per-epoch train/eval progress bars on stderr (reference client UX)")
    sync_every: int = field(32, help="device→host loss sync cadence in steps; also the training-health sentinel check point (DSML_SENTINELS — docs/OBSERVABILITY.md)")


# The per-epoch bar is ``utils.metrics.ProgressBar`` (the reference
# client's schollz/progressbar UX, client.go:584-590/467-473): TTY-aware
# — in-place redraws on an interactive stderr, one newline-terminated
# summary line per bar otherwise — and off unless ``TrainConfig.progress``
# (a redraw per batch is host-side noise the compiled step loop doesn't
# need by default).


def _make_optimizer(cfg: TrainConfig, steps_per_epoch: int) -> optax.GradientTransformation:
    from dsml_tpu.utils.schedules import make_schedule, wrap_with_plateau

    total = max(cfg.epochs * steps_per_epoch, 1)
    lr = make_schedule(cfg.lr_schedule, cfg.lr, total, cfg.warmup_steps)
    opt = {
        "sgd": lambda: optax.sgd(lr),
        "momentum": lambda: optax.sgd(lr, momentum=0.9),
        "adam": lambda: optax.adam(lr),
        "adamw": lambda: optax.adamw(lr, weight_decay=1e-4),
    }[cfg.optimizer]()
    if cfg.lr_schedule == "plateau":
        # the reference-documented "adaptive learning rate scheduler":
        # monitor the per-step loss, decay when it stops improving
        # one accumulated loss evaluation per epoch; patience counts epochs
        opt = wrap_with_plateau(
            opt,
            factor=cfg.plateau_factor,
            patience=cfg.plateau_patience,
            accumulation_size=max(steps_per_epoch, 1),
        )
    return opt


class Trainer:
    """Train any model exposing ``init(seed)``, ``loss(params,x,y)``,
    ``apply(params,x)`` data-parallel over a mesh."""

    def __init__(self, model, config: TrainConfig | None = None, mesh=None):
        self.model = model
        self.config = config or TrainConfig()
        self.mesh = mesh if mesh is not None else data_mesh(self.config.dp or None)
        self.metrics = MetricsLogger(self.config.log_metrics or None)
        self._step_fn = None
        self._eval_fn = None
        self._ef_norm_fn = None

    def _build(self, steps_per_epoch: int):
        optimizer = _make_optimizer(self.config, steps_per_epoch)
        # 0 → "auto" (DSML_BUCKET_MB default), < 0 → None (single buffer)
        bucket = self.config.bucket_mb
        self._step_fn = make_dp_train_step(
            self.model.loss, optimizer, self.mesh, algorithm=self.config.algorithm,
            bucket_size_mb="auto" if bucket == 0 else (None if bucket < 0 else bucket),
            error_feedback=self.config.error_feedback,
        )
        self._eval_fn = make_eval_step(self.model, self.mesh)
        return optimizer

    def train(self, data: Dataset, params=None):
        cfg = self.config
        n_dp = self.mesh.shape.get("dp", 1)
        if cfg.batch_size % max(n_dp, 1):
            raise ValueError(f"global batch {cfg.batch_size} not divisible by dp={n_dp}")
        steps_per_epoch = data.n_train // cfg.batch_size
        optimizer = self._build(steps_per_epoch)
        if params is None:
            params = self.model.init(cfg.seed)
        else:
            # The jitted step donates its inputs; copy so the caller's arrays
            # survive the first step.
            params = jax.tree.map(lambda a: jax.numpy.array(a), params)
        opt_state = optimizer.init(params)
        ef = None
        if cfg.error_feedback:
            # per-rank compression residuals (EF-SGD): sharded over dp so
            # each device stores only its own; checkpointable state below
            from dsml_tpu.parallel.bucketing import init_error_feedback

            ef = init_error_feedback(params, self.mesh, "dp")

        ckpt = None
        start_epoch = 1
        resume_skip = 0  # batches already consumed of start_epoch (mid-epoch resume)
        if cfg.checkpoint_dir:
            from dsml_tpu.checkpoint import CheckpointManager

            ckpt = CheckpointManager(cfg.checkpoint_dir,
                                     max_to_keep=cfg.keep_checkpoints)
            if cfg.resume and ckpt.latest_step() is None:
                foreign = [n for n in os.listdir(ckpt.directory) if n.isdigit()]
                if foreign:
                    # digit-named step dirs = the orbax layout the previous
                    # Checkpointer wrote; restarting silently would redo
                    # every completed epoch
                    raise RuntimeError(
                        f"resume=True but {cfg.checkpoint_dir} holds no native "
                        f"checkpoints — found orbax-format step dirs {foreign[:3]}; "
                        "restore them via utils.checkpoint.Checkpointer("
                        "backend='orbax') or start a fresh checkpoint_dir"
                    )
            if cfg.resume and ckpt.latest_step() is not None:
                template = {"params": params, "opt_state": opt_state,
                            "meta": {"epoch": 0}}
                if ef is not None:
                    # EF residuals ride the manifest like any state tree;
                    # restoring them is what keeps a kill-and-resume under
                    # quantized sync bit-identical to the unkilled run
                    template["ef"] = ef
                state = ckpt.restore(template=template)
                params, opt_state = state["params"], state["opt_state"]
                if ef is not None:
                    ef = state["ef"]
                it_state = ckpt.iterator_state() or {}
                if int(it_state.get("consumed", 0)) > 0:
                    # mid-epoch checkpoint (save_every_steps): restart
                    # INSIDE the epoch — shard_batches re-derives the same
                    # shuffle from (seed + epoch), and fast-forwarding past
                    # the consumed prefix makes the remaining batches
                    # bit-identical to the uninterrupted run's
                    start_epoch = int(it_state["epoch"])
                    resume_skip = int(it_state["consumed"])
                    log.info("resumed mid-epoch %d at batch %d",
                             start_epoch, resume_skip)
                else:
                    start_epoch = int(state["meta"]["epoch"]) + 1
                    log.info("resumed from checkpoint at epoch %d", start_epoch - 1)

        # Observability (docs/OBSERVABILITY.md): when the registry is
        # enabled, the loop records a per-step breakdown (data /
        # step_dispatch / loss_sync / checkpoint_stall — the fused jitted
        # step is one program, so fwd-bwd/sync/opt split lives in
        # `bench.py --section obs`) and goodput = productive step time ÷
        # wall across resume/checkpoint events. Disabled: one boolean per
        # step, nothing recorded.
        # Failure forensics (docs/OBSERVABILITY.md § Failure forensics), all
        # opt-in and zero-sync by construction:
        # - sentinels (DSML_SENTINELS) inspect the loss at the EXISTING
        #   loss_sync point — the scalar is already host-ready there, so the
        #   fused step gains no device→host round trips;
        # - hangwatch (DSML_HANGWATCH) arms a deadline per loss-sync window
        #   at k× the trailing-median window wall, once warmed up;
        # - the flight recorder gets one "step" event per batch and one
        #   "loss_sync" per sync.
        obs_reg = get_registry()
        recorder = flight_recorder.get_flight_recorder()
        sentinels = TrainingSentinels.maybe_from_env()
        hw_cfg = hangwatch.config_from_env()
        hw = hangwatch.get_hangwatch() if hw_cfg is not None else None
        measure_act = os.environ.get("DSML_MEASURE_ACT") == "1"
        if sentinels is not None or hw is not None or measure_act:
            # forensic env opt-in IMPLIES observability: a halt bundle with
            # empty event/metric/log sections would defeat the black-box
            # recorder the operator just asked for (and a measured
            # activation claim on a disabled registry would vanish before
            # plan_mesh could read it). Enable the registry and
            # install the crash/SIGTERM dump hooks + the log ring
            # (idempotent; previous hooks are chained, obs.disable restores)
            from dsml_tpu.utils.logging import install_ring_handler

            obs_reg.enable()
            install_ring_handler()
            flight_recorder.install()
        track = obs_reg.enabled
        goodput = GoodputTracker(registry=obs_reg) if track else None
        breakdown = StepBreakdown(registry=obs_reg) if track else None
        ledger = get_memory_ledger(obs_reg)
        if track:
            # memory ledger (docs/OBSERVABILITY.md § Memory ledger):
            # attribute the training state at its allocation site — the
            # per-device resident bytes of params / optimizer state / EF
            # residuals; per-step peak watermarks land at loss syncs below
            ledger.claim_tree("params", params)
            ledger.claim_tree("optimizer", opt_state)
            if ef is not None:
                ledger.claim_tree("error_feedback", ef)
        if measure_act:
            self._measure_activation_footprint(
                params, data.train_x[: cfg.batch_size],
                data.train_y[: cfg.batch_size], ledger, recorder,
            )
        if track and start_epoch > 1:
            goodput.mark("restore", epoch=start_epoch - 1)
        step_deadline = (hangwatch.TrailingDeadline.from_config(hw_cfg)
                         if hw_cfg is not None else None)
        sync_every = max(cfg.sync_every, 1)
        save_every_steps = max(cfg.save_every_steps, 0)
        global_step = (start_epoch - 1) * steps_per_epoch + resume_skip
        recorder.record(
            "train_start", epochs=cfg.epochs, batch_size=cfg.batch_size,
            steps_per_epoch=steps_per_epoch, algorithm=cfg.algorithm,
            start_epoch=start_epoch,
        )

        def save_ckpt(epochs_done: int, it_epoch: int, consumed_now: int,
                      wait: bool = False) -> None:
            """THE checkpoint write, shared by all three call sites
            (mid-epoch, epoch boundary, final) so the id scheme and
            manifest layout cannot drift apart: id = GLOBAL STEP when
            step-granularity saves are on (one monotonic id space), the
            completed-epoch number otherwise; the loader position
            (it_epoch, consumed_now) rides the manifest. With wait=False
            the step loop pays only the synchronous host snapshot +
            enqueue (the commit rides the writer thread and surfaces as
            checkpoint_commit_ms)."""
            t_save = time.perf_counter()
            state = {"params": params, "opt_state": opt_state,
                     "meta": {"epoch": epochs_done}}
            if ef is not None:
                state["ef"] = ef
            ckpt.save(global_step if save_every_steps else epochs_done,
                      state,
                      iterator_state={"epoch": it_epoch,
                                      "consumed": consumed_now},
                      wait=wait)
            if track:
                breakdown.add("checkpoint_stall", time.perf_counter() - t_save)
                goodput.mark("checkpoint_save", epoch=it_epoch,
                             step=global_step)
            recorder.record(
                "checkpoint_save", epoch=it_epoch, step=global_step,
                stall_ms=round((time.perf_counter() - t_save) * 1e3, 3))

        history = []
        t0 = time.monotonic()
        train_body_done = False
        try:
            for epoch in range(start_epoch, cfg.epochs + 1):
                losses = []  # device arrays; synced only every sync_every steps so
                # dispatch of step k+1 overlaps execution of step k without the
                # in-flight queue growing unboundedly
                batches = prefetch_batches(
                    shard_batches(data.train_x, data.train_y, cfg.batch_size, seed=cfg.seed + epoch)
                )
                skip = resume_skip if epoch == start_epoch else 0
                if skip:
                    import itertools

                    # fast-forward the deterministic stream past the consumed
                    # prefix — the prefetcher never over-advances the recorded
                    # position (ResumableIterator's contract, inlined)
                    batches = itertools.islice(batches, skip, None)
                consumed = skip
                bar = ProgressBar(steps_per_epoch - skip,
                                  desc=f"Epoch {epoch}/{cfg.epochs}",
                                  enabled=cfg.progress)
                epoch_t0 = time.monotonic()
                t_prev = time.perf_counter()
                # Hangwatch covers the SYNC WINDOW, not single batches: async
                # dispatch makes 31 of every 32 batch walls sub-ms (only the
                # sync_every-th blocks in block_until_ready), so a per-batch
                # median would collapse the deadline to the floor and fire on
                # every healthy sync. The window wall — sync to sync — is the
                # unimodal quantity a wedged collective actually stretches.
                hw_token = None
                win_t0 = t_prev
                try:
                    for x, y in batches:
                        global_step += 1
                        consumed += 1
                        if hw is not None and hw_token is None:
                            deadline_s = step_deadline.timeout_s()
                            if deadline_s is not None:
                                hw_token = hw.arm("train_sync_window", deadline_s,
                                                  step=global_step, epoch=epoch)
                        if track:
                            t_data = time.perf_counter()
                            breakdown.add("data", t_data - t_prev)
                        if ef is not None:
                            params, opt_state, ef, loss = self._step_fn(
                                params, opt_state, ef, x, y)
                        else:
                            params, opt_state, loss = self._step_fn(params, opt_state, x, y)
                        if track:
                            t_disp = time.perf_counter()
                            breakdown.add("step_dispatch", t_disp - t_data)
                        losses.append(loss)
                        bar.update()
                        if len(losses) % sync_every == 0:
                            losses[-1].block_until_ready()
                            if track:
                                breakdown.add("loss_sync", time.perf_counter() - t_disp)
                                # per-step peak watermark at the existing
                                # sync point (the step already blocked —
                                # no new device round trips; statless
                                # backends record the claimed total)
                                ledger.note_step_peak(global_step)
                            if hw is not None:
                                if hw_token is not None:
                                    hw.disarm(hw_token)
                                    hw_token = None
                                now_sync = time.perf_counter()
                                step_deadline.observe(now_sync - win_t0)
                                win_t0 = now_sync
                            if sentinels is not None or track:
                                # the scalar is already synced; float() is a host read
                                loss_host = float(losses[-1])
                                recorder.record("loss_sync", step=global_step,
                                                epoch=epoch, loss=loss_host)
                                if sentinels is not None:
                                    # halt-policy trips raise SentinelTripped out of
                                    # train() with the postmortem bundle already on disk
                                    sentinels.check(global_step, loss_host)
                            if track and ef is not None:
                                # residual health at the existing sync point
                                # (the step already blocked — one small
                                # jitted norm + host read per sync window)
                                if self._ef_norm_fn is None:
                                    self._ef_norm_fn = jax.jit(optax.global_norm)
                                obs_reg.gauge(
                                    "quant_error_feedback_norm",
                                    "global L2 norm of the error-feedback "
                                    "residual tree (sampled at loss syncs)",
                                ).set(float(self._ef_norm_fn(ef)))
                        if track:
                            now = time.perf_counter()
                            breakdown.note_step_wall(now - t_prev)
                            recorder.record("step", step=global_step, epoch=epoch,
                                            wall_ms=round((now - t_prev) * 1e3, 3))
                            t_prev = now
                        if (ckpt is not None and save_every_steps
                                and consumed < steps_per_epoch
                                and global_step % save_every_steps == 0):
                            # mid-epoch preemption point: resume
                            # fast-forwards past the consumed prefix
                            # bit-identically
                            save_ckpt(epoch - 1, epoch, consumed)
                            if track:
                                t_prev = time.perf_counter()  # save ≠ data time
                finally:
                    # disarm on EVERY exit — a halt/exception (or epoch end with
                    # a partial window) must not leave a deadline that later
                    # fires a spurious hang bundle
                    if hw_token is not None:
                        hw.disarm(hw_token)
                bar.close()
                if track:
                    # productive = time spent driving steps; eval/logging/
                    # checkpoint overhead shows up as the goodput gap
                    goodput.add_productive(time.monotonic() - epoch_t0)
                em = EpochMetrics()
                for loss in losses:
                    em.update(float(loss), 0, cfg.batch_size)
                train_acc = self.evaluate(params, data.train_x, data.train_y)
                # Same log shape as the reference's per-epoch line (client.go:650-652).
                log.info("Epoch %d: Average Loss = %.4f, Accuracy = %.2f%%", epoch, em.avg_loss, train_acc * 100)
                recorder.record("epoch", epoch=epoch, avg_loss=em.avg_loss,
                                train_accuracy=train_acc)
                history.append(
                    self.metrics.log(epoch=epoch, avg_loss=em.avg_loss, train_accuracy=train_acc)
                )
                if ckpt is not None and epoch % max(cfg.save_every, 1) == 0:
                    # async: the write overlaps the next epoch's compute; the
                    # manager's writer barrier (or close()) commits it. Saves
                    # land at epoch boundaries, so the loader position is just
                    # the NEXT epoch's seed — shard_batches re-derives the
                    # shuffle from (cfg.seed + epoch), making resume
                    # bit-identical to the uninterrupted run
                    save_ckpt(epoch, epoch, 0)
            last_epoch = cfg.epochs
            if ckpt is not None:
                # final state must always be persisted, even when epochs isn't a
                # multiple of save_every (otherwise the reported model is lost and
                # resume would redo the last epochs)
                if last_epoch >= start_epoch and last_epoch % max(cfg.save_every, 1) != 0:
                    save_ckpt(last_epoch, last_epoch, 0, wait=True)
            train_body_done = True
        except BaseException as e:
            # a device OOM unwinding through here leaves a postmortem
            # whose memory.json carries the ledger snapshot + watermark
            # timeline (docs/OBSERVABILITY.md § Memory ledger); any other
            # exception passes untouched (the crash hooks own those)
            if track:
                try:
                    maybe_dump_oom(e)
                except Exception:  # noqa: BLE001 — never mask the real crash
                    pass
            raise
        finally:
            if ckpt is not None:
                # ALWAYS flush: a dying run (preemption signal unwinding,
                # sentinel halt) still commits its queued async saves — that
                # checkpoint is exactly what recovery resumes from. A writer
                # error must not mask the original exception.
                try:
                    ckpt.close()
                except Exception:
                    if train_body_done:
                        raise
                    log.warning("checkpoint close failed during exception "
                                "unwind", exc_info=True)
        test_acc = self.evaluate(
            params, data.test_x, data.test_y,
            progress_label="Testing" if cfg.progress else None,
        )
        wall = time.monotonic() - t0
        epochs_run = max(cfg.epochs - start_epoch + 1, 0)  # resume skips earlier epochs
        samples = epochs_run * steps_per_epoch * cfg.batch_size
        log.info("Final Test Accuracy: %.2f%%", test_acc * 100)  # client.go:500-501 shape
        final = {"test_accuracy": test_acc, "wall_time_s": wall,
                 "samples_per_sec": samples / max(wall, 1e-9)}
        if track:
            gsum = goodput.summary()
            obs_reg.gauge("train_goodput", "productive/wall of the last run") \
                .set(gsum["goodput"])
            final["obs_goodput"] = gsum
            final["obs_step_breakdown"] = breakdown.summary()
        self.metrics.log(**final)
        return params, history, test_acc

    def _measure_activation_footprint(self, params, x, y, ledger,
                                      recorder) -> None:
        """``DSML_MEASURE_ACT=1``: measure the train step's XLA temp bytes
        from shapes alone (``parallel.auto.measured_activation_bytes`` —
        compile-only, no data, no execution) and claim them as the
        ledger's ``activations`` subsystem, so the activation-budget
        number ``plan_mesh`` consumes exists without a manual call. The
        extra compile is the opt-in's price; failure logs and trains on —
        a broken measurement must never block the run it instruments."""
        from dsml_tpu.parallel.auto import measured_activation_bytes

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        try:
            measured = measured_activation_bytes(
                self.model.loss, jax.tree.map(sds, params), sds(x), sds(y)
            )
        except Exception:
            log.warning("DSML_MEASURE_ACT: activation measurement failed",
                        exc_info=True)
            return
        if measured is None:
            log.warning(
                "DSML_MEASURE_ACT: backend reports no compiled memory "
                "analysis — activation footprint stays analytic"
            )
            return
        # claim + geometry: plan_mesh rescales per-sample to ITS
        # batch_per_device instead of reusing this absolute number
        ledger.record_activation_measurement(measured, x.shape[0])
        recorder.record("activation_measured", bytes=int(measured),
                        batch=int(x.shape[0]))
        log.info("measured activation footprint: %.2f MB (XLA temp bytes "
                 "of the compiled step)", measured / 1e6)

    def evaluate(self, params, x: np.ndarray, y: np.ndarray, batch_size: int = 2048,
                 progress_label: str | None = None) -> float:
        n_dp = max(self.mesh.shape.get("dp", 1), 1)
        n = x.shape[0]
        usable = n - (n % n_dp)  # each eval batch must split evenly over dp
        bs = max(batch_size - batch_size % n_dp, n_dp)
        bar = ProgressBar((usable + bs - 1) // bs,
                          desc=progress_label or "Testing",
                          enabled=progress_label is not None)
        correct = 0
        for start in range(0, usable, bs):
            xb, yb = x[start : start + bs], y[start : start + bs]
            if xb.shape[0] % n_dp:  # tail: trim to a dp multiple
                cut = xb.shape[0] - xb.shape[0] % n_dp
                xb, yb = xb[:cut], yb[:cut]
            correct += int(self._eval_fn(params, xb, yb))
            bar.update()
        bar.close()
        return correct / max(usable, 1)
