"""Flight recorder: a bounded ring of recent events + postmortem bundles.

The obs registry answers "how fast are we?"; this module answers "why did
the run die at 3am?". Every hot path appends small structured events
(step results, span closes, collective plans, checkpoint commits,
health-probe outcomes) into a bounded, thread-safe ring buffer — a few µs
per event, nothing when the registry is disabled — and on failure the
recorder dumps a SELF-CONTAINED postmortem bundle: the trailing events,
the full registry snapshot, the Chrome span trace, a config/mesh/env
fingerprint, the log tail (``utils.logging`` ring handler), and Python
stack dumps of every live thread.

Bundles land in ``DSML_POSTMORTEM_DIR`` (default ``postmortem/``), one
directory per dump::

    postmortem/20260804T031502_12345_unhandled_exception_1/
        MANIFEST.json       # reason, time, exception, file inventory
        events.jsonl        # the ring buffer, oldest → newest
        registry.json       # Registry.collect() snapshot
        trace.json          # SpanTracer.chrome_trace()
        fingerprint.json    # python/jax/env/argv/devices
        stacks.txt          # all-thread Python stacks
        log_tail.jsonl      # last N log records

Dump triggers — installed by :func:`install` (which ``obs.enable()``
calls) and removed by :func:`uninstall`:

- unhandled exceptions (``sys.excepthook`` + ``threading.excepthook``,
  chaining to the previous hooks);
- SIGTERM — the preemption signal — chaining to the prior handler so the
  process still terminates;
- hard crashes via ``faulthandler`` into ``<dir>/faulthandler.log``;
- on demand (:meth:`FlightRecorder.dump`), which also backs the sentinel
  ``dump``/``halt`` policies and the hangwatch expiry path.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import re
import signal
import sys
import threading
import time
import traceback

from dsml_tpu.obs.registry import Registry, get_registry

__all__ = [
    "FlightRecorder",
    "get_flight_recorder",
    "record",
    "install",
    "uninstall",
    "installed",
    "postmortem_dir",
]

DEFAULT_CAPACITY = 2048


def postmortem_dir() -> str:
    """Where bundles go: ``DSML_POSTMORTEM_DIR`` or ``./postmortem``."""
    return os.environ.get("DSML_POSTMORTEM_DIR", "postmortem")


def _event_capacity() -> int:
    try:
        cap = int(os.environ.get("DSML_FLIGHT_EVENTS", DEFAULT_CAPACITY))
    except ValueError:
        return DEFAULT_CAPACITY
    return cap if cap > 0 else DEFAULT_CAPACITY


def _all_thread_stacks() -> str:
    """Python stacks of every live thread, newest frame last — the
    ``py-spy dump`` a postmortem needs when the process is already gone."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = []
    for ident, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {names.get(ident, '?')} (ident {ident}) ---")
        lines.extend(ln.rstrip("\n") for ln in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


def _fingerprint() -> dict:
    """Config/mesh/env identity of the process. jax facts are read ONLY
    when jax is already imported — a dump must never initialize a backend
    (the dead-tunnel hang it exists to document)."""
    fp = {
        "python": sys.version,
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "pid": os.getpid(),
        "env": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith(("DSML_", "JAX_", "XLA_", "BENCH_", "TPU_"))
        },
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        fp["jax_version"] = getattr(jax, "__version__", "?")
        try:
            devs = jax.devices()
            fp["devices"] = {
                "count": len(devs),
                "platform": devs[0].platform if devs else "?",
            }
        except Exception as e:  # noqa: BLE001 — backend may be half-dead
            fp["devices"] = {"error": repr(e)[:200]}
    return fp


def _sanitize(reason: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", reason)[:64] or "dump"


class FlightRecorder:
    """Bounded thread-safe event ring + bundle writer.

    :meth:`record` is the hot-path write: one enabled-check, then a dict
    build and a deque append under a lock. :meth:`dump` always works —
    even with the registry disabled an explicit dump writes whatever is
    buffered (possibly nothing) plus the live snapshots.
    """

    def __init__(self, capacity: int | None = None,
                 registry: Registry | None = None,
                 directory: str | None = None):
        self.registry = registry if registry is not None else get_registry()
        # instance-level default bundle dir (None = DSML_POSTMORTEM_DIR,
        # read at dump time so the env var can change mid-run)
        self.directory = directory
        self._events: collections.deque = collections.deque(
            maxlen=capacity if capacity else _event_capacity()
        )
        self._lock = threading.Lock()
        self._seq = 0
        self._dump_seq = 0

    @property
    def enabled(self) -> bool:
        return self.registry.enabled

    def record(self, kind: str, **fields) -> None:
        """Append one event; no-op (one branch) when the registry is off."""
        if not self.registry.enabled:
            return
        with self._lock:
            self._seq += 1
            self._events.append(
                {"seq": self._seq, "t": round(time.time(), 6),
                 "kind": kind, **fields}
            )

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # -- bundles -----------------------------------------------------------

    def dump(self, reason: str, exc: BaseException | None = None,
             directory: str | None = None, extra: dict | None = None) -> str:
        """Write a complete postmortem bundle; returns its directory.

        Never raises into the failing path it documents: per-file write
        errors are swallowed into the manifest's ``errors`` list (a broken
        disk must not mask the original crash)."""
        base = (directory if directory is not None
                else self.directory if self.directory is not None
                else postmortem_dir())
        with self._lock:
            self._dump_seq += 1
            n = self._dump_seq
        stamp = time.strftime("%Y%m%dT%H%M%S")
        path = os.path.join(
            base, f"{stamp}_{os.getpid()}_{_sanitize(reason)}_{n}"
        )
        os.makedirs(path, exist_ok=True)
        errors: list[str] = []

        def write(name: str, fn) -> None:
            try:
                with open(os.path.join(path, name), "w") as f:
                    fn(f)
            except Exception as e:  # noqa: BLE001
                errors.append(f"{name}: {e!r}"[:300])

        events = self.events()
        write("events.jsonl", lambda f: f.writelines(
            json.dumps(e) + "\n" for e in events
        ))
        write("registry.json", lambda f: json.dump(
            self.registry.collect(), f, indent=1
        ))

        def write_trace(f):
            from dsml_tpu.obs.spans import get_tracer

            json.dump(get_tracer().chrome_trace(), f)

        write("trace.json", write_trace)
        write("fingerprint.json", lambda f: json.dump(_fingerprint(), f, indent=1))
        write("stacks.txt", lambda f: f.write(_all_thread_stacks()))

        def write_memory(f):
            # the ledger snapshot + watermark timeline + live source
            # readings (page-pool state rides as the kv_pages details) —
            # resolved through THIS recorder's registry, so a private
            # bench recorder never leaks the process ledger's claims
            from dsml_tpu.obs.memory import get_memory_ledger

            json.dump(get_memory_ledger(self.registry).snapshot(), f, indent=1)

        write("memory.json", write_memory)

        def write_log_tail(f):
            from dsml_tpu.utils.logging import get_ring_handler

            handler = get_ring_handler()
            f.writelines(
                json.dumps(r) + "\n"
                for r in (handler.records() if handler is not None else [])
            )

        write("log_tail.jsonl", write_log_tail)

        manifest = {
            "reason": reason,
            "time": time.time(),
            "pid": os.getpid(),
            "event_count": len(events),
            "files": sorted(
                n for n in os.listdir(path) if n != "MANIFEST.json"
            ),
        }
        if exc is not None:
            manifest["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc)[:2000],
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__
                ),
            }
        if extra:
            manifest["extra"] = extra
        if errors:
            manifest["errors"] = errors
        write("MANIFEST.json", lambda f: json.dump(manifest, f, indent=1))

        # count even on a disabled registry? No: the counter write itself
        # no-ops there, which is fine — the bundle on disk is the record.
        self.registry.counter(
            "postmortem_dumps_total", "postmortem bundles written",
            labels=("reason",),
        ).inc(reason=_sanitize(reason))
        return path


_default: FlightRecorder | None = None
_default_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    """The process-default recorder (bound to the default registry)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = FlightRecorder()
    return _default


def record(kind: str, **fields) -> None:
    """``flight_recorder.record("step", step=k, ...)`` against the default
    recorder; one branch when observability is off."""
    get_flight_recorder().record(kind, **fields)


# ---------------------------------------------------------------------------
# crash-hook installation (sys.excepthook / threading.excepthook / SIGTERM /
# faulthandler) — obs.enable() installs, obs.disable() tears down
# ---------------------------------------------------------------------------

_install_lock = threading.Lock()
_installed = False
_prev_excepthook = None
_prev_threading_hook = None
_prev_sigterm = None
_sigterm_hooked = False
_fault_file = None
_fault_was_enabled = False


def installed() -> bool:
    return _installed


def install(recorder: FlightRecorder | None = None) -> None:
    """Install the dump triggers. Idempotent; chains previous hooks so it
    composes with pytest / user handlers. Signal installation silently
    skips off the main thread (the interpreter forbids it there)."""
    global _installed, _prev_excepthook, _prev_threading_hook
    global _prev_sigterm, _sigterm_hooked, _fault_file, _fault_was_enabled
    with _install_lock:
        if _installed:
            return
        rec = recorder if recorder is not None else get_flight_recorder()

        _prev_excepthook = sys.excepthook

        def excepthook(etype, value, tb):
            try:
                e = value if isinstance(value, BaseException) else None
                # a SentinelTripped (or any bundle-carrying exception)
                # already wrote its postmortem at trip time — a second
                # near-identical unhandled_exception bundle is pure churn
                if getattr(e, "bundle", None) is None:
                    from dsml_tpu.obs.memory import is_oom

                    # an OOM-shaped death is named as one, so the bundle
                    # directory itself says "memory" before anyone opens
                    # memory.json
                    rec.dump(
                        "resource_exhausted" if is_oom(e)
                        else "unhandled_exception",
                        exc=e,
                    )
            except Exception:  # noqa: BLE001 — never mask the real crash
                pass
            _prev_excepthook(etype, value, tb)

        sys.excepthook = excepthook

        _prev_threading_hook = threading.excepthook

        def thread_hook(args):
            try:
                rec.dump(
                    "thread_exception", exc=args.exc_value,
                    extra={"thread": getattr(args.thread, "name", "?")},
                )
            except Exception:  # noqa: BLE001
                pass
            _prev_threading_hook(args)

        threading.excepthook = thread_hook

        try:
            _prev_sigterm = signal.getsignal(signal.SIGTERM)

            def on_sigterm(signum, frame):
                try:
                    rec.dump("sigterm")
                except Exception:  # noqa: BLE001
                    pass
                prev = _prev_sigterm
                if callable(prev):
                    prev(signum, frame)
                elif prev is signal.SIG_IGN:
                    # the app deliberately ignores SIGTERM; dumping must not
                    # change that — bundle written, process lives on
                    return
                else:
                    # SIG_DFL (or an unknowable C-level handler): restore the
                    # default disposition and re-deliver so the exit status
                    # still says "killed by SIGTERM"
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_sigterm)
            _sigterm_hooked = True
        except ValueError:
            _sigterm_hooked = False  # not the main thread

        # hard-crash (segfault / fatal signal) C-level stacks: faulthandler
        # into a persistent file under the postmortem base dir
        try:
            base = postmortem_dir()
            os.makedirs(base, exist_ok=True)
            _fault_was_enabled = faulthandler.is_enabled()
            _fault_file = open(  # noqa: SIM115 — must outlive this frame
                os.path.join(base, "faulthandler.log"), "a"
            )
            faulthandler.enable(file=_fault_file)
        except OSError:
            _fault_file = None

        _installed = True


def uninstall() -> None:
    """Tear down cleanly: restore prior hooks/handlers, hand faulthandler
    back to whoever (e.g. pytest) had it enabled before."""
    global _installed, _prev_excepthook, _prev_threading_hook
    global _prev_sigterm, _sigterm_hooked, _fault_file
    with _install_lock:
        if not _installed:
            return
        sys.excepthook = _prev_excepthook
        threading.excepthook = _prev_threading_hook
        if _sigterm_hooked:
            try:
                signal.signal(signal.SIGTERM, _prev_sigterm)
            except ValueError:
                pass
            _sigterm_hooked = False
        if _fault_file is not None:
            if _fault_was_enabled:
                faulthandler.enable()  # back to stderr (pytest's setup)
            else:
                faulthandler.disable()
            _fault_file.close()
            _fault_file = None
        _prev_excepthook = _prev_threading_hook = _prev_sigterm = None
        _installed = False
