"""dsml_tpu.obs — unified observability: metrics, spans, goodput/MFU.

One subsystem for the accounting the reference coordinator treated as its
core product (device health, per-algorithm all-reduce latency) and the
accounting a production TPU trainer actually needs (step-time breakdown,
goodput across preemptions, MFU):

- :mod:`~dsml_tpu.obs.registry` — process-wide thread-safe metrics
  registry (counters / gauges / fixed-bound histograms, labeled), JSONL +
  Prometheus-text exposition. DISABLED by default (``DSML_OBS=1`` or
  :func:`enable` turns it on); disabled writes cost one branch.
- :mod:`~dsml_tpu.obs.spans` — nestable host-side span tracer with
  ``block_until_ready`` fencing, Chrome trace-event JSON export, per-span
  p50/p90 summaries.
- :mod:`~dsml_tpu.obs.step_stats` — per-step phase breakdown, goodput
  (productive ÷ wall across preemption/restore), MFU from
  ``models.common`` FLOP estimates.
- :mod:`~dsml_tpu.obs.export` — rotation-safe JSONL sink
  (:class:`MetricsLogger`) + opt-in HTTP ``/metrics`` endpoint.

Failure forensics (``docs/OBSERVABILITY.md`` § Failure forensics):

- :mod:`~dsml_tpu.obs.flight_recorder` — bounded ring of recent
  structured events; dumps a self-contained postmortem bundle (events
  JSONL + registry snapshot + Chrome trace + env fingerprint + log tail
  + all-thread stacks) on unhandled exception, SIGTERM, or on demand.
- :mod:`~dsml_tpu.obs.sentinels` — NaN/Inf-loss, grad-norm-explosion and
  loss-spike sentinels with per-sentinel ``warn``/``dump``/``halt``
  policies (``DSML_SENTINELS``), checked at the trainer's existing
  ``loss_sync`` point.
- :mod:`~dsml_tpu.obs.hangwatch` — armable deadline watchdog
  (``DSML_HANGWATCH``): trainer per loss-sync window, coordinator per
  wire op, checkpoint writer per commit; expiry dumps stacks + a bundle.

Cluster plane (``docs/OBSERVABILITY.md`` § Cluster):

- :mod:`~dsml_tpu.obs.cluster` — cross-process aggregation: identity-
  stamped snapshots, exact-sum counter / bucket-wise histogram merge into
  ONE fleet exposition with ``host``/``pid``/``role`` labels, fleet
  goodput + straggler ranking, and Chrome-trace stitching with
  handshake-based clock-offset alignment (HTTP scrape of
  ``start_metrics_server``'s ``/cluster.json`` or gRPC pull/push over the
  ``comm/`` ObsPlane service).
- :mod:`~dsml_tpu.obs.regress` — perf-regression gate over the committed
  ``BENCH_r*.json`` history (median ± k·MAD noise bands); ``python -m
  dsml_tpu.obs.regress`` exits nonzero on regression and exports the
  calibrated collective-latency profile for the cost-model planner.

Memory ledger (``docs/OBSERVABILITY.md`` § Memory ledger):

- :mod:`~dsml_tpu.obs.memory` — per-subsystem device-byte attribution
  (:class:`MemoryLedger`): static claims at allocation sites (params /
  optimizer / EF residuals / measured activation temps) + weakly-held
  live sources (KV page pools, migration/checkpoint staging), reconciled
  against ``jax.Device.memory_stats()`` at scrape time with an
  ``hbm_unattributed_bytes`` residual gauge and explicit provenance
  (``hbm_source``). Per-step peak watermarks ride postmortem bundles
  (``memory.json``); OOM-shaped crashes dump through
  :func:`~dsml_tpu.obs.memory.maybe_dump_oom`.

Request tracing + SLO budgets (``docs/OBSERVABILITY.md`` § Request
tracing & SLO budgets):

- :class:`~dsml_tpu.obs.spans.TraceContext` — request-scoped trace
  identity minted at ``Router.submit`` and propagated through prefill
  dispatch, the handoff codec/donor headers, decode injection, and
  retire/requeue; every stage emits trace-tagged spans + Chrome flow
  events so the stitched timeline renders one request as a causal chain.
- :mod:`~dsml_tpu.obs.slo` — per-SLOClass SLI windows, rolling error
  budgets with multi-window (fast/slow) burn-rate status, per-class
  goodput counters, and the p99 tail-attribution report (which stage —
  queue/prefill/handoff/first-decode/decode — dominates the tail);
  merged fleet-wide by ``MergedView.report()``. Tail-bucket histogram
  samples carry trace_id EXEMPLARS in the JSONL/``/metrics.json``
  expositions.

Metric names, label sets, and the span taxonomy are specified in
``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

from dsml_tpu.obs import flight_recorder, hangwatch, sentinels  # noqa: F401
from dsml_tpu.obs.export import (  # noqa: F401
    MetricsLogger,
    MetricsServer,
    start_metrics_server,
)
from dsml_tpu.obs.flight_recorder import (  # noqa: F401
    FlightRecorder,
    get_flight_recorder,
)
from dsml_tpu.obs.hangwatch import HangWatch, TrailingDeadline, get_hangwatch  # noqa: F401
from dsml_tpu.obs.memory import (  # noqa: F401
    MemoryLedger,
    get_memory_ledger,
)
from dsml_tpu.obs.registry import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS_MS,
    Counter,
    Gauge,
    Histogram,
    ObsUnavailable,
    Registry,
    enabled,
    get_registry,
)
from dsml_tpu.obs.registry import disable as _registry_disable
from dsml_tpu.obs.registry import enable as _registry_enable
from dsml_tpu.obs.sentinels import (  # noqa: F401
    SentinelConfig,
    SentinelTripped,
    TrainingSentinels,
)
from dsml_tpu.obs.spans import (  # noqa: F401
    SpanTracer,
    TraceContext,
    get_tracer,
    span,
)
from dsml_tpu.obs.step_stats import (  # noqa: F401
    STEP_PHASES,
    GoodputTracker,
    StepBreakdown,
    mfu,
)

__all__ = [
    "Registry", "Counter", "Gauge", "Histogram", "ObsUnavailable",
    "get_registry", "enable", "disable", "enabled",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "SpanTracer", "TraceContext", "span", "get_tracer",
    "StepBreakdown", "GoodputTracker", "mfu", "STEP_PHASES",
    "MetricsLogger", "MetricsServer", "start_metrics_server",
    "record_collective_plan", "observe_collective_latency_ms",
    "observe_recovery_ms", "record_quant_sync_bytes",
    "FlightRecorder", "get_flight_recorder", "dump_postmortem",
    "MemoryLedger", "get_memory_ledger",
    "SentinelConfig", "SentinelTripped", "TrainingSentinels",
    "HangWatch", "TrailingDeadline", "get_hangwatch",
    "ClockSync", "ClusterAggregator", "merge_snapshots", "snapshot",
    "stitch_traces", "trace_summary",
]

# cluster-plane names resolve lazily (PEP 562): ``python -m
# dsml_tpu.obs.cluster`` would otherwise warn about the module being
# imported as a side effect of its own package __init__
_CLUSTER_NAMES = ("ClockSync", "ClusterAggregator", "merge_snapshots",
                  "snapshot", "stitch_traces", "trace_summary")


def __getattr__(name: str):
    if name in _CLUSTER_NAMES:
        from dsml_tpu.obs import cluster as _cluster

        return getattr(_cluster, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def enable(forensics: bool = True) -> None:
    """Turn observability on: flip the default registry live and (unless
    ``forensics=False``) install the failure-forensics layer — the
    flight-recorder crash hooks (``sys.excepthook`` / SIGTERM /
    ``faulthandler``) and the ring-buffer log handler whose tail rides in
    every postmortem bundle. ``disable()`` tears all of it down."""
    _registry_enable()
    if forensics:
        from dsml_tpu.utils.logging import install_ring_handler

        install_ring_handler()
        flight_recorder.install()


def disable() -> None:
    """Turn observability off and tear down the forensics hooks installed
    by :func:`enable` (prior excepthook/signal/faulthandler dispositions
    are restored)."""
    from dsml_tpu.utils.logging import uninstall_ring_handler

    flight_recorder.uninstall()
    uninstall_ring_handler()
    _registry_disable()


def dump_postmortem(reason: str = "on_demand",
                    directory: str | None = None) -> str:
    """Write a postmortem bundle NOW (works even with the registry
    disabled); returns the bundle directory."""
    return get_flight_recorder().dump(reason, directory=directory)


def record_collective_plan(algorithm: str, tree, bucket_size_mb,
                           axis: str = "dp",
                           registry: Registry | None = None) -> None:
    """Record a gradient-sync bucket plan's shape (bucket count, per-bucket
    and total bytes) labeled by collective algorithm + mesh axis.

    Called from INSIDE step builders at trace time: shapes/dtypes are
    static there, so this runs once per compilation — never per step —
    and costs nothing while tracing is the price already being paid.
    ``bucket_size_mb=None`` records ONE bucket of the tree's total bytes
    — the dp/hybrid single-buffer ``ravel_pytree`` path (raw leaf bytes;
    its dtype promotion is not modeled). Callers whose ``None`` means
    per-dtype buckets (zero2) resolve it to ``float("inf")`` first."""
    reg = registry if registry is not None else get_registry()
    if not reg.enabled:
        return
    if bucket_size_mb is None:
        import math

        import jax
        import jax.numpy as jnp

        sizes = [sum(
            math.prod(l.shape) * jnp.dtype(jnp.result_type(l)).itemsize
            for l in jax.tree.leaves(tree)
        )]
        n_buckets = 1
    else:
        from dsml_tpu.parallel.bucketing import plan_buckets

        plan = plan_buckets(tree, bucket_size_mb)
        sizes = [plan.bucket_nbytes(b) for b in range(plan.n_buckets)]
        n_buckets = plan.n_buckets
    labels = {"algorithm": algorithm, "axis": axis}
    reg.counter(
        "collective_sync_compiles_total",
        "gradient-sync step compilations", labels=("algorithm", "axis"),
    ).inc(**labels)
    reg.gauge(
        "collective_sync_buckets",
        "buckets per gradient sync", labels=("algorithm", "axis"),
    ).set(n_buckets, **labels)
    reg.gauge(
        "collective_sync_bytes",
        "total gradient bytes per sync", labels=("algorithm", "axis"),
    ).set(sum(sizes), **labels)
    hist = reg.histogram(
        "collective_bucket_bytes",
        "per-bucket payload bytes", labels=("algorithm", "axis"),
        buckets=(1 << 16, 1 << 18, 1 << 20, 1 << 22, 1 << 24, 1 << 26, 1 << 28),
    )
    for nbytes in sizes:
        hist.observe(nbytes, **labels)
    # one trace-time event per compile: a postmortem shows WHICH sync plan
    # (algorithm / bucket count / payload) the dying step was running.
    # Default-registry callers only — a private registry (bench isolation)
    # must not leak its plans into the process-global ring
    if reg is get_registry():
        flight_recorder.record(
            "collective_plan", algorithm=algorithm, axis=axis,
            buckets=n_buckets, bytes=int(sum(sizes)),
        )


def record_quant_sync_bytes(bytes_by_scheme: dict, algorithm: str,
                            axis: str = "dp",
                            registry: Registry | None = None) -> None:
    """One quantized gradient sync's wire bytes →
    ``collective_quant_bytes_total{scheme,algorithm,axis}``.

    ``bytes_by_scheme`` is the ANALYTIC per-sync byte count from
    ``parallel.bucketing.plan_quant_wire_bytes`` (static shapes ⇒ exact).
    The dp/zero2 frontends call this once per step from the host-side
    dispatch wrapper — a dict walk and one no-op-able counter write, never
    a device sync — so the counter is a true cumulative total, unlike the
    trace-time plan gauges which record once per compile."""
    reg = registry if registry is not None else get_registry()
    if not reg.enabled or not bytes_by_scheme:
        return
    c = reg.counter(
        "collective_quant_bytes_total",
        "wire bytes shipped by quantized gradient syncs (analytic per-sync "
        "count; fp32 rows are the uncompressed buckets riding along)",
        labels=("scheme", "algorithm", "axis"),
    )
    for scheme, nbytes in bytes_by_scheme.items():
        c.inc(nbytes, scheme=scheme, algorithm=algorithm, axis=axis)


def observe_recovery_ms(stage: str, ms: float,
                        registry: Registry | None = None) -> None:
    """One elastic-recovery latency sample →
    ``controller_recovery_ms{stage}`` (stages: ``reconfigure`` /
    ``checkpoint_fallback`` / ``grow_keep`` / ``grow_replay``) — the
    distribution behind the chaos bench's recovery p50/p99
    (``bench.py --section chaos``, docs/ELASTIC.md)."""
    reg = registry if registry is not None else get_registry()
    if not reg.enabled:
        return
    reg.histogram(
        "controller_recovery_ms",
        "elastic-controller recovery latency", labels=("stage",),
    ).observe(ms, stage=stage)


def observe_collective_latency_ms(algorithm: str, ms: float,
                                  payload_bytes: int | None = None,
                                  axis: str = "dp",
                                  registry: Registry | None = None) -> None:
    """One measured collective latency sample →
    ``collective_latency_ms{algorithm,axis}`` (the EQuARX-style
    per-algorithm accounting surface; ``utils.tracing.ring_latency_ms``
    and ``bench.py --section obs`` feed it)."""
    reg = registry if registry is not None else get_registry()
    if not reg.enabled:
        return
    reg.histogram(
        "collective_latency_ms",
        "measured all-reduce latency", labels=("algorithm", "axis"),
    ).observe(ms, algorithm=algorithm, axis=axis)
    if payload_bytes is not None:
        reg.counter(
            "collective_latency_sampled_bytes_total",
            "payload bytes of measured collectives", labels=("algorithm", "axis"),
        ).inc(payload_bytes, algorithm=algorithm, axis=axis)
