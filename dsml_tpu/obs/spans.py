"""Nestable span tracing with Chrome trace-event export.

``jax.profiler`` captures device timelines but needs a working profiler
backend (see ``utils.tracing.trace``); these spans are the host-side
complement: cheap, dependency-free wall-clock intervals around the
phases a training/serving loop actually schedules (data fetch, step
dispatch, grad sync, checkpoint stall). Spans nest through a
thread-local stack, optionally FENCE on device values before closing
(``fence=`` pytree → ``block_until_ready``, so a span around a jitted
call measures execution, not dispatch), and export two ways:

- :meth:`SpanTracer.chrome_trace` — Chrome trace-event JSON (duration
  ``B``/``E`` pairs, microsecond ``ts``), loadable in ``chrome://tracing``
  / Perfetto;
- :meth:`SpanTracer.summaries` — per-span-name count/p50/p90 (ms),
  backed by the registry histogram ``span_ms{name=...}``.

Disabled-registry runs pay one branch per span and record nothing.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from dsml_tpu.obs import flight_recorder
from dsml_tpu.obs.registry import Registry, get_registry

__all__ = ["SpanTracer", "span", "get_tracer"]

# cap on retained trace events (B+E pairs): a week-long run must not grow
# host memory; the newest events win because the deque drops oldest first
_EVENT_CAP = 200_000


class SpanTracer:
    """Collects spans into trace events + a per-name latency histogram."""

    def __init__(self, registry: Registry | None = None):
        self.registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._hist = self.registry.histogram(
            "span_ms", "host-side span durations", labels=("name",)
        )

    # perf_counter is monotonic and sub-µs; one common origin per tracer so
    # every event's ts is comparable
    _t0 = time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, fence=None, **args):
        """Trace ``name`` around the block. ``fence``: a jax array/pytree to
        ``block_until_ready`` before the span closes (measure execution, not
        dispatch). Extra kwargs land in the event's ``args``. Nesting is
        carried by B/E event order per thread, matching Chrome's duration-
        event semantics."""
        if not self.registry.enabled:
            yield self
            return
        tid = threading.get_ident()
        begin = {
            "name": name, "ph": "B", "ts": self._now_us(),
            "pid": os.getpid(), "tid": tid,
        }
        if args:
            begin["args"] = {k: str(v) for k, v in args.items()}
        with self._lock:
            self._append(begin)
        try:
            yield self
        finally:
            if fence is not None:
                import jax

                jax.block_until_ready(fence)
            end_ts = self._now_us()
            with self._lock:
                self._append({"name": name, "ph": "E", "ts": end_ts,
                              "pid": os.getpid(), "tid": tid})
            ms = (end_ts - begin["ts"]) / 1e3
            self._hist.observe(ms, name=name)
            # span closes ride in the flight-recorder ring, so a postmortem
            # shows what phases ran right before the failure — but only for
            # tracers on the DEFAULT registry: a private tracer (bench/test
            # isolation) must not interleave into the process-global ring
            if self.registry is get_registry():
                flight_recorder.record("span", name=name, ms=round(ms, 3))

    def _append(self, event: dict) -> None:
        self._events.append(event)
        if len(self._events) > _EVENT_CAP:
            # amortized eviction: cut the oldest quarter, then drop 'E'
            # events whose 'B' fell in the cut — orphaned ends would make
            # chrome://tracing mis-nest the whole remaining trace. (Old B
            # events whose E survives stay matched; only E-without-B can
            # result from dropping a prefix.)
            del self._events[: _EVENT_CAP // 4]
            kept, stacks = [], {}
            for e in self._events:
                stack = stacks.setdefault(e["tid"], [])
                if e["ph"] == "B":
                    stack.append(e["name"])
                elif e["ph"] == "E":
                    if not stack or stack[-1] != e["name"]:
                        continue  # its B was evicted — drop the orphan
                    stack.pop()
                kept.append(e)
            self._events = kept

    # -- export ------------------------------------------------------------

    def chrome_trace(self) -> dict:
        """``chrome://tracing``-loadable dict. Events are sorted by ``ts``
        (concurrent threads append under one lock, but their clock reads
        race the append order)."""
        with self._lock:
            events = sorted(self._events, key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def summaries(self) -> dict:
        """{span name: {count, mean, p50, p90, p99} (ms)}."""
        out = {}
        for key, _ in self._hist._items():
            (name,) = key
            out[name] = self._hist.summary(name=name)
        return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


_default_tracer: SpanTracer | None = None
_default_lock = threading.Lock()


def get_tracer() -> SpanTracer:
    """The process-default tracer (bound to the default registry)."""
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = SpanTracer()
    return _default_tracer


def span(name: str, fence=None, **args):
    """``with obs.span("grad_sync"): ...`` against the default tracer."""
    return get_tracer().span(name, fence=fence, **args)
