"""Nestable span tracing with Chrome trace-event export.

``jax.profiler`` captures device timelines but needs a working profiler
backend (see ``utils.tracing.trace``); these spans are the host-side
complement: cheap, dependency-free wall-clock intervals around the
phases a training/serving loop actually schedules (data fetch, step
dispatch, grad sync, checkpoint stall). Spans nest through a
thread-local stack, optionally FENCE on device values before closing
(``fence=`` pytree → ``block_until_ready``, so a span around a jitted
call measures execution, not dispatch), and export two ways:

- :meth:`SpanTracer.chrome_trace` — Chrome trace-event JSON (duration
  ``B``/``E`` pairs, microsecond ``ts``), loadable in ``chrome://tracing``
  / Perfetto;
- :meth:`SpanTracer.summaries` — per-span-name count/p50/p90 (ms),
  backed by the registry histogram ``span_ms{name=...}``.

Disabled-registry runs pay one branch per span and record nothing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import itertools
import json
import os
import random
import threading
import time

from dsml_tpu.obs import flight_recorder
from dsml_tpu.obs.registry import Registry, get_registry

__all__ = ["SpanTracer", "TraceContext", "span", "get_tracer"]

# cap on retained trace events (B+E pairs): a week-long run must not grow
# host memory; the newest events win because the deque drops oldest first
_EVENT_CAP = 200_000

_trace_seq = itertools.count()
# minting runs on the serving submit path: a PRNG seeded once from the
# OS (not per-call urandom) keeps the per-request bill in the low-µs
_trace_rng = random.Random(os.urandom(8))

# os.getpid() is a real syscall (µs-scale under sandboxed kernels) and
# every trace event stamps a pid — cache it, refreshed in fork children
# so forked workers still stamp their own lane
_PID = os.getpid()


def _refresh_pid() -> None:
    global _PID
    _PID = os.getpid()


if hasattr(os, "register_at_fork"):  # POSIX
    os.register_at_fork(after_in_child=_refresh_pid)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Request-scoped trace identity, minted once (at ``Router.submit``)
    and propagated through every stage a request touches — prefill
    dispatch, the handoff codec/donor headers, decode injection, retire/
    requeue. ``trace_id`` is the request's globally unique identity;
    ``span_id`` names the PARENT span at the propagation point so a child
    process can record causality, not just membership.

    The context is plain data (two strings) so it serializes into any
    header dict (:meth:`to_header`/:meth:`from_header`) and costs nothing
    when observability is off — span/flow emission is gated separately by
    the registry switch."""

    trace_id: str
    span_id: str = ""

    @classmethod
    def mint(cls, span_id: str = "") -> "TraceContext":
        # pid + process-local sequence + random tail: unique across a
        # fleet of routers without coordination, stable length, greppable
        seq = next(_trace_seq)
        return cls(
            trace_id=f"{_PID:x}-{seq:x}-"
                     f"{_trace_rng.getrandbits(48):012x}",
            span_id=span_id,
        )

    def child(self, span_id: str) -> "TraceContext":
        """Same trace, new parent span — what a stage hands downstream."""
        return TraceContext(trace_id=self.trace_id, span_id=span_id)

    @property
    def flow_id(self) -> int:
        """Stable 48-bit Chrome flow-event id derived from the trace_id:
        every process that carries this context emits flow events under
        the SAME id, so the stitched timeline links the request's spans
        across pid lanes without any id negotiation. Memoized per
        instance (frozen dataclass — the memo rides ``__dict__`` via
        ``object.__setattr__``): flows are emitted per request hop."""
        cached = self.__dict__.get("_flow_id")
        if cached is None:
            digest = hashlib.blake2b(self.trace_id.encode(), digest_size=6)
            cached = int.from_bytes(digest.digest(), "big")
            object.__setattr__(self, "_flow_id", cached)
        return cached

    @property
    def flow_id_hex(self) -> str:
        cached = self.__dict__.get("_flow_id_hex")
        if cached is None:
            cached = f"{self.flow_id:x}"
            object.__setattr__(self, "_flow_id_hex", cached)
        return cached

    def to_header(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_header(cls, header) -> "TraceContext | None":
        if not header or not header.get("trace_id"):
            return None
        return cls(trace_id=str(header["trace_id"]),
                   span_id=str(header.get("span_id", "")))


def _arg_value(v):
    """Span-arg codec: int/float stay NUMERIC so Chrome viewers and the
    stitcher can sort/aggregate on them; everything else (trace ids
    included) stringifies. bool is an int subclass — keep it readable."""
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, (int, float)):
        return v
    return str(v)


class SpanTracer:
    """Collects spans into trace events + a per-name latency histogram."""

    def __init__(self, registry: Registry | None = None):
        self.registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._hist = self.registry.histogram(
            "span_ms", "host-side span durations", labels=("name",)
        )

    # perf_counter is monotonic and sub-µs; one common origin per tracer so
    # every event's ts is comparable
    _t0 = time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, fence=None, **args):
        """Trace ``name`` around the block. ``fence``: a jax array/pytree to
        ``block_until_ready`` before the span closes (measure execution, not
        dispatch). Extra kwargs land in the event's ``args``. Nesting is
        carried by B/E event order per thread, matching Chrome's duration-
        event semantics."""
        if not self.registry.enabled:
            yield self
            return
        tid = threading.get_ident()
        begin = {
            "name": name, "ph": "B", "ts": self._now_us(),
            "pid": _PID, "tid": tid,
        }
        if args:
            begin["args"] = {k: _arg_value(v) for k, v in args.items()}
        with self._lock:
            self._append(begin)
        try:
            yield self
        finally:
            if fence is not None:
                import jax

                jax.block_until_ready(fence)
            end_ts = self._now_us()
            with self._lock:
                self._append({"name": name, "ph": "E", "ts": end_ts,
                              "pid": _PID, "tid": tid})
            ms = (end_ts - begin["ts"]) / 1e3
            self._hist.observe(ms, name=name)
            # span closes ride in the flight-recorder ring, so a postmortem
            # shows what phases ran right before the failure — but only for
            # tracers on the DEFAULT registry: a private tracer (bench/test
            # isolation) must not interleave into the process-global ring
            if self.registry is get_registry():
                flight_recorder.record("span", name=name, ms=round(ms, 3))

    def instant(self, name: str, **args) -> None:
        """One zero-duration instant event (Chrome ``ph="i"``) — the
        retire/abandon/requeue lifecycle marks request tracing rides on."""
        if not self.registry.enabled:
            return
        event = {"name": name, "ph": "i", "s": "t",
                 "ts": self._now_us(), "pid": _PID,
                 "tid": threading.get_ident()}
        if args:
            event["args"] = {k: _arg_value(v) for k, v in args.items()}
        with self._lock:
            self._append(event)

    _FLOW_PH = {"start": "s", "step": "t", "end": "f"}

    def flow(self, name: str, ctx: "TraceContext", phase: str = "step",
             **args) -> None:
        """One Chrome FLOW event bound to ``ctx``'s flow id: ``start`` at
        the minting stage, ``step`` at every hop (prefill done, handoff
        landed, requeue), ``end`` at retirement. Every process carrying
        the same :class:`TraceContext` emits under the same id, so the
        stitched cross-process timeline draws the request as one causal
        chain of arrows (``obs.cluster.stitch_traces``)."""
        if not self.registry.enabled:
            return
        ph = self._FLOW_PH.get(phase)
        if ph is None:
            raise ValueError(
                f"flow phase must be one of {sorted(self._FLOW_PH)}, "
                f"got {phase!r}"
            )
        flow_args = {"trace_id": ctx.trace_id}
        if args:
            for k, v in args.items():
                flow_args[k] = _arg_value(v)
        event = {
            "name": name, "ph": ph, "cat": "request",
            "id": ctx.flow_id_hex, "ts": self._now_us(),
            "pid": _PID, "tid": threading.get_ident(),
            "args": flow_args,
        }
        if ph == "f":
            event["bp"] = "e"  # bind the arrow to the enclosing slice
        with self._lock:
            self._append(event)

    def request_span(self, name: str, ctx: "TraceContext | None",
                     fence=None, flow: str | None = None, **args):
        """:meth:`span` tagged with a request's trace identity (plus an
        optional flow event emitted inside the span, so Chrome binds the
        arrow to this slice). ``ctx=None`` degrades to a plain span —
        call sites never branch on whether a request carries a trace.

        Request spans ride a lean class-based path (one lock hold for
        B + flow, no flight-recorder write — the serving layer records
        its own admit/retire/requeue flight events): the per-request
        tracing bill is budgeted at < 1% of a decode tick and
        ``bench.py --section request_tracing`` enforces it."""
        if flow is not None and flow not in self._FLOW_PH:
            # validate eagerly (like :meth:`flow`): __enter__ only looks
            # the phase up when obs is ENABLED, so a call-site typo would
            # otherwise pass every disabled run and crash the serving hot
            # path the first time DSML_OBS=1
            raise ValueError(
                f"flow phase must be one of {sorted(self._FLOW_PH)}, "
                f"got {flow!r}"
            )
        if ctx is None:
            return self.span(name, fence=fence, **args)
        return _RequestSpan(self, name, ctx, fence, flow, args)

    def _append(self, event: dict) -> None:
        self._events.append(event)
        if len(self._events) > _EVENT_CAP:
            # amortized eviction: cut the oldest quarter, then drop 'E'
            # events whose 'B' fell in the cut — orphaned ends would make
            # chrome://tracing mis-nest the whole remaining trace. (Old B
            # events whose E survives stay matched; only E-without-B can
            # result from dropping a prefix.)
            del self._events[: _EVENT_CAP // 4]
            kept, stacks = [], {}
            for e in self._events:
                stack = stacks.setdefault(e["tid"], [])
                if e["ph"] == "B":
                    stack.append(e["name"])
                elif e["ph"] == "E":
                    if not stack or stack[-1] != e["name"]:
                        continue  # its B was evicted — drop the orphan
                    stack.pop()
                kept.append(e)
            self._events = kept

    # -- export ------------------------------------------------------------

    def chrome_trace(self) -> dict:
        """``chrome://tracing``-loadable dict. Events are sorted by ``ts``
        (concurrent threads append under one lock, but their clock reads
        race the append order)."""
        with self._lock:
            events = sorted(self._events, key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def summaries(self) -> dict:
        """{span name: {count, mean, p50, p90, p99} (ms)}."""
        out = {}
        for key, _ in self._hist._items():
            (name,) = key
            out[name] = self._hist.summary(name=name)
        return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


class _RequestSpan:
    """Class-based context manager for trace-tagged spans: emits the B
    event (and optional flow event) under ONE lock hold on enter, the E
    event + ``span_ms`` sample on exit. Exists because request tracing
    runs per request on the serving hot path — the generator-contextmanager
    plumbing of :meth:`SpanTracer.span` costs more than the events."""

    __slots__ = ("tracer", "name", "ctx", "fence", "flow", "args", "_t0",
                 "_live")

    def __init__(self, tracer, name, ctx, fence, flow, args):
        self.tracer = tracer
        self.name = name
        self.ctx = ctx
        self.fence = fence
        self.flow = flow
        self.args = args
        self._t0 = 0.0
        self._live = False

    def __enter__(self):
        tracer = self.tracer
        if not tracer.registry.enabled:
            return tracer
        self._live = True
        ctx = self.ctx
        tid = threading.get_ident()
        pid = _PID
        ts = tracer._now_us()
        self._t0 = ts
        span_args = {"trace_id": ctx.trace_id,
                     "parent_span": ctx.span_id or self.name}
        for k, v in self.args.items():
            span_args[k] = _arg_value(v)
        begin = {"name": self.name, "ph": "B", "ts": ts, "pid": pid,
                 "tid": tid, "args": span_args}
        events = [begin]
        if self.flow is not None:
            flow_ev = {
                "name": self.name, "ph": SpanTracer._FLOW_PH[self.flow],
                "cat": "request", "id": ctx.flow_id_hex, "ts": ts,
                "pid": pid, "tid": tid,
                "args": {"trace_id": ctx.trace_id},
            }
            if flow_ev["ph"] == "f":
                flow_ev["bp"] = "e"
            events.append(flow_ev)
        with tracer._lock:
            for e in events:
                tracer._append(e)
        return tracer

    def __exit__(self, exc_type, exc, tb):
        if not self._live:
            return False
        if self.fence is not None:
            import jax

            jax.block_until_ready(self.fence)
        tracer = self.tracer
        end_ts = tracer._now_us()
        with tracer._lock:
            tracer._append({"name": self.name, "ph": "E", "ts": end_ts,
                            "pid": _PID,
                            "tid": threading.get_ident()})
        # request spans deliberately do NOT feed span_ms: their latency
        # distributions already land in the serving_* histograms
        # (admission/TTFT/TPOT/prefill-chunk), and the per-request tracing
        # bill is budgeted against a decode tick — no duplicate sample
        return False


_default_tracer: SpanTracer | None = None
_default_lock = threading.Lock()


def get_tracer() -> SpanTracer:
    """The process-default tracer (bound to the default registry)."""
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = SpanTracer()
    return _default_tracer


def span(name: str, fence=None, **args):
    """``with obs.span("grad_sync"): ...`` against the default tracer."""
    return get_tracer().span(name, fence=fence, **args)
