"""Process-wide metrics registry: counters, gauges, histograms.

The reference coordinator's whole value-add beyond moving bytes was
*accounting* — it health-monitored devices and reported per-algorithm
all-reduce latency (``NaiveAllReduce``'s ``totalTimeMs`` /
``totalDataTransferred``). This module is that accounting surface grown
into a first-class subsystem: one thread-safe registry per process,
metrics labeled by collective algorithm / bucket index / mesh axis, with
JSONL and Prometheus-text exposition (``docs/OBSERVABILITY.md``).

Zero-overhead-by-default contract: the registry starts DISABLED unless
``DSML_OBS`` is set truthy; every write op early-returns on a single
attribute check, so instrumented hot paths cost one branch when off
(``bench.py --section obs`` guards the <1% bar). Enabling is a runtime
switch (:func:`enable`) — no re-wiring, the same metric objects go live.

Histograms use FIXED bucket bounds (cumulative, Prometheus-style) plus a
bounded raw-sample tail for p50/p90 summaries; both expositions are
generated from the same snapshot, so the two formats cannot drift.
"""

from __future__ import annotations

import bisect
import collections
import json
import os
import threading
import time
import weakref

__all__ = [
    "ObsUnavailable",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "enable",
    "disable",
    "enabled",
    "DEFAULT_LATENCY_BUCKETS_MS",
]


class ObsUnavailable(RuntimeError):
    """An observability backend (jax.profiler capture, the HTTP exporter)
    is unavailable in this environment. The message always carries
    remediation text — callers surface it verbatim instead of an opaque
    backend traceback."""


# ms-scale latency bounds: sub-ms collectives through multi-second compiles.
DEFAULT_LATENCY_BUCKETS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# raw-sample tail per labeled histogram series, for p50/p90 summaries
# (bounded so a long run cannot grow host memory without bound)
_SAMPLE_CAP = 4096


def _label_key(label_names: tuple, labels: dict) -> tuple:
    if set(labels) != set(label_names):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared {sorted(label_names)}"
        )
    return tuple(str(labels[n]) for n in label_names)


class _Metric:
    """Shared base: a named family of labeled series."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str,
                 label_names: tuple):
        self._registry = registry
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def _items(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return list(self._series.items())


class _BoundCounter:
    """One labeled counter series with the label key resolved ONCE —
    the fast path for per-request hot paths (``Counter.bind``): an inc
    costs the enabled branch + one lock, no per-call label validation."""

    __slots__ = ("_counter", "_key")

    def __init__(self, counter: "Counter", key: tuple):
        self._counter = counter
        self._key = key

    def inc(self, value: float = 1.0) -> None:
        c = self._counter
        if not c._registry._enabled:
            return
        with c._lock:
            c._series[self._key] = c._series.get(self._key, 0.0) + value


class Counter(_Metric):
    """Monotonically increasing count (events, bytes, errors)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if not self._registry._enabled:
            return
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease by {value}")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def bind(self, **labels) -> _BoundCounter:
        """Pre-resolve a label set (validated HERE, once) into a bound
        series handle whose ``inc`` skips per-call label work."""
        return _BoundCounter(self, _label_key(self.label_names, labels))

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(self.label_names, labels), 0.0))


class Gauge(_Metric):
    """Point-in-time value (queue depth, slot occupancy, goodput)."""

    kind = "gauge"

    def clear(self) -> None:
        """Drop every labeled series. For scrape-time re-derived gauges
        whose LABEL SETS change between scrapes (the memory ledger's
        per-subsystem claims, its provenance flag): without a clear, a
        series whose source died — or whose provenance flipped — would
        freeze at its last value in every later exposition."""
        with self._lock:
            self._series.clear()

    def set(self, value: float, **labels) -> None:
        if not self._registry._enabled:
            return
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def value(self, **labels) -> float | None:
        with self._lock:
            v = self._series.get(_label_key(self.label_names, labels))
        return None if v is None else float(v)


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "samples", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 = the +Inf overflow bucket
        self.sum = 0.0
        self.count = 0
        self.samples = collections.deque(maxlen=_SAMPLE_CAP)
        # bucket index -> {"value", "trace_id", "time"}: the LAST traced
        # sample that landed in each bucket. One dict per bucket (not a
        # tail list) bounds memory while guaranteeing the interesting
        # property: a tail bucket's count always resolves to a concrete
        # trace_id — "what request WAS that p99?" has an answer
        self.exemplars: dict[int, dict] = {}


class Histogram(_Metric):
    """Fixed-bound histogram with a bounded raw tail for percentiles."""

    kind = "histogram"

    def __init__(self, registry, name, help, label_names,
                 buckets: tuple | None = None):
        super().__init__(registry, name, help, label_names)
        bounds = tuple(sorted(
            float(b) for b in (buckets if buckets is not None
                               else DEFAULT_LATENCY_BUCKETS_MS)
        ))
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket bound")
        self.buckets = bounds

    def observe(self, value: float, exemplar: str | None = None,
                **labels) -> None:
        """``exemplar`` (a trace_id) attaches the sample's request identity
        to the bucket it lands in (last-wins per bucket) — the link from a
        tail-latency number to the distributed trace that produced it,
        exposed through ``collect()``/JSONL/``/metrics.json``."""
        if not self._registry._enabled:
            return
        value = float(value)
        key = _label_key(self.label_names, labels)
        idx = bisect.bisect_left(self.buckets, value)  # first bound >= value
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistSeries(len(self.buckets))
            series.counts[idx] += 1
            series.sum += value
            series.count += 1
            series.samples.append(value)
            if exemplar is not None:
                series.exemplars[idx] = {
                    "value": value, "trace_id": str(exemplar),
                    "time": time.time(),
                }

    def summary(self, **labels) -> dict:
        """count / sum / mean / p50 / p90 over the (bounded) raw tail."""
        with self._lock:
            series = self._series.get(_label_key(self.label_names, labels))
            if series is None or not series.count:
                return {"count": 0}
            samples = sorted(series.samples)
            total, count = series.sum, series.count

        def pct(q: float) -> float:
            return samples[min(int(q * len(samples)), len(samples) - 1)]

        return {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6),
            "p50": round(pct(0.50), 6),
            "p90": round(pct(0.90), 6),
            "p99": round(pct(0.99), 6),
        }


class Registry:
    """Thread-safe metric namespace. ``get_registry()`` returns the
    process-wide default; tests/benches may hold private instances."""

    def __init__(self, enabled: bool = False):
        self._enabled = bool(enabled)
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collect_hooks: list = []  # weakrefs, pruned on collect

    # -- lifecycle ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        """Drop every metric (tests; a fresh bench section)."""
        with self._lock:
            self._metrics.clear()

    # -- metric constructors (get-or-create) -------------------------------

    def _get(self, cls, name: str, help: str, labels: tuple, **kw) -> _Metric:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(self, name, help, tuple(labels), **kw)
                return metric
        if type(metric) is not cls or metric.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered as {metric.kind} with "
                f"labels {metric.label_names}"
            )
        # EXPLICIT bucket bounds must match the registered histogram's —
        # silently reusing the first registration's bounds would pile, e.g.,
        # occupancy ratios into a ms-latency ladder. Omitting buckets
        # (buckets=None) always fetches, whatever the bounds.
        want = kw.get("buckets")
        if want is not None and metric.buckets != tuple(sorted(float(b) for b in want)):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{metric.buckets}"
            )
        return metric

    def counter(self, name: str, help: str = "", labels: tuple = ()) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: tuple = ()) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: tuple = (),
                  buckets: tuple | None = None) -> Histogram:
        """``buckets=None`` = the default ms-latency ladder when creating,
        and no-bounds-check when fetching an existing histogram."""
        return self._get(Histogram, name, help, labels, buckets=buckets)

    # -- exposition --------------------------------------------------------

    def add_collect_hook(self, fn) -> None:
        """Register ``fn`` to run at the top of every exposition
        (``collect``/``to_prometheus_text``). For owners of DERIVED
        point-in-time gauges (``obs.slo``'s rolling burn-rate/status)
        whose value depends on the clock, not just on ingest: without a
        scrape-time refresh, a gauge last exported during a burst would
        FREEZE at that value once the class's traffic stops — an idle
        class would page forever. Held by weak reference: the hook dies
        with its owner (no unregister needed, no cross-test leaks)."""
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        with self._lock:
            self._collect_hooks.append(ref)

    def _run_collect_hooks(self) -> None:
        with self._lock:
            refs = list(self._collect_hooks)
        dead = [r for r in refs if r() is None]
        for r in refs:
            fn = r()
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass  # a broken refresher must not break exposition
        if dead:
            with self._lock:
                self._collect_hooks = [
                    r for r in self._collect_hooks if r not in dead
                ]

    def collect(self) -> list[dict]:
        """Point-in-time snapshot: one record per labeled series."""
        self._run_collect_hooks()
        with self._lock:
            metrics = list(self._metrics.values())
        out = []
        for m in metrics:
            for key, series in m._items():
                labels = dict(zip(m.label_names, key))
                if isinstance(m, Histogram):
                    cumulative, running = {}, 0
                    for bound, c in zip(m.buckets, series.counts):
                        running += c
                        cumulative[str(bound)] = running
                    cumulative["+Inf"] = running + series.counts[-1]
                    rec = {
                        "name": m.name, "type": m.kind, "labels": labels,
                        "buckets": cumulative,
                        "sum": series.sum, "count": series.count,
                        "summary": m.summary(**labels),
                    }
                    if series.exemplars:
                        # snapshot under the metric lock: observe() inserts
                        # new bucket keys concurrently, and iterating a
                        # live dict across a resize raises RuntimeError
                        # (the unlocked counts/sum reads are torn-read-
                        # benign; a dict iteration is not)
                        with m._lock:
                            ex_items = sorted(series.exemplars.items())
                        # keyed by bucket BOUND (the exposition's own
                        # vocabulary), not internal index
                        rec["exemplars"] = {
                            ("+Inf" if i == len(m.buckets)
                             else str(m.buckets[i])): dict(ex)
                            for i, ex in ex_items
                        }
                    out.append(rec)
                else:
                    out.append({
                        "name": m.name, "type": m.kind, "labels": labels,
                        "value": series,
                    })
        return out

    def to_jsonl(self) -> str:
        """One JSON record per labeled series, timestamped."""
        now = time.time()
        return "\n".join(
            json.dumps({"time": now, **rec}) for rec in self.collect()
        )

    def dump_jsonl(self, path: str) -> None:
        text = self.to_jsonl()
        if text:
            with open(path, "a") as f:
                f.write(text + "\n")

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._run_collect_hooks()
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            items = m._items()
            if not items:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, series in items:
                pairs = dict(zip(m.label_names, key))
                if isinstance(m, Histogram):
                    running = 0
                    for bound, c in zip(m.buckets, series.counts):
                        running += c
                        lines.append(
                            f"{m.name}_bucket{_fmt_labels({**pairs, 'le': bound})} {running}"
                        )
                    lines.append(
                        f"{m.name}_bucket{_fmt_labels({**pairs, 'le': '+Inf'})} "
                        f"{series.count}"
                    )
                    lines.append(f"{m.name}_sum{_fmt_labels(pairs)} {_fmt_num(series.sum)}")
                    lines.append(f"{m.name}_count{_fmt_labels(pairs)} {series.count}")
                else:
                    lines.append(f"{m.name}{_fmt_labels(pairs)} {_fmt_num(series)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_labels(pairs: dict) -> str:
    if not pairs:
        return ""
    body = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(pairs.items())
    )
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


_default = Registry(
    enabled=os.environ.get("DSML_OBS", "").lower() not in ("", "0", "false", "off")
)


def get_registry() -> Registry:
    return _default


def enable() -> None:
    _default.enable()


def disable() -> None:
    _default.disable()


def enabled() -> bool:
    return _default.enabled
