"""Perf-regression gate over the committed BENCH history.

``BENCH_r01..r05.json`` record five rounds of bench output, but nothing
machine-checks a fresh run against them — "did this PR make the benches
worse?" has been a human squinting at JSON. This module is the
machine-checkable answer:

- :func:`extract_metrics` — best-effort metric extraction from every
  artifact shape the history actually contains: full bench records with
  ``parsed`` payloads, rc=124 timeouts with bare tails, and 2000-byte
  tail TRUNCATIONS that cut the final JSON line mid-record (r03/r05) —
  a strict parser would call three of five rounds empty;
- :func:`compare` — per-metric noise bands (median ± k·MAD over the
  history, with a relative floor so an all-identical history doesn't
  produce a zero-width band) and a direction table (tokens/s up is good,
  step-ms up is bad; config constants like batch sizes are never gated);
- :func:`export_profile` — the calibrated collective-latency constants
  (ring/naive p50, e2e wire path, payload) as a machine-readable profile
  JSON for the ROADMAP's SCALE-Sim-style cost-model planner, sourced
  from the bench history and/or an aggregated cluster snapshot's
  ``collective_latency_ms`` histograms;
- ``python -m dsml_tpu.obs.regress`` — the CI gate: exits nonzero on a
  regression, 0 clean, 2 when nothing was parseable; ``--report-only``
  always exits 0 but still writes the report artifact.

Thresholds and the direction table are documented in
``docs/OBSERVABILITY.md`` § Perf-regression gate.
"""

from __future__ import annotations

import glob
import json
import os
import re

__all__ = [
    "compare",
    "export_profile",
    "extract_metrics",
    "main",
    "metric_direction",
    "noise_band",
    "profile_from_merged",
]

REPORT_SCHEMA = "dsml.obs.regress_report/1"
PROFILE_SCHEMA = "dsml.obs.collective_profile/1"

# defaults; the CLI exposes all three
DEFAULT_K = 5.0          # band half-width in MADs
DEFAULT_REL_FLOOR = 0.10  # ... but never narrower than ±10% of |median|
DEFAULT_MIN_HISTORY = 3   # fewer samples -> "insufficient_history", not gated

# a history this noisy carries no regression signal: MAD/|median| above
# this ratio marks the metric "too_noisy" and exempts it from gating
# (BENCH_r01's warm-cache mnist row is 270x its successors — a band wide
# enough to admit that spread would admit anything)
NOISE_CEILING = 0.5


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

# one positional token stream over (possibly truncated) JSON text:
# headline names ('"metric": "NAME"') and numeric '"key": value' pairs —
# the trailing lookahead rejects a number cut off by the tail boundary
_TOKEN_RE = re.compile(
    r'"metric":\s*"([A-Za-z_][A-Za-z0-9_]*)"'
    r'|"([A-Za-z_][A-Za-z0-9_]*)":\s*(-?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)'
    r"(?=\s*[,}\]])"
)
# bookkeeping keys that are record structure, not metrics
_STRUCTURE_KEYS = frozenset({"n", "rc", "time", "value"})


def _scan_text(text: str, out: dict) -> None:
    """Fold numeric pairs from (possibly truncated) JSON text into ``out``
    — later occurrences win, matching "the final emitted line is the
    record". A ``"value": V`` maps onto the most recent PRECEDING
    ``"metric": NAME`` only: a truncated multi-record tail can cut one
    record's value off entirely, and last-headline-wins would then hand
    another record's value to the wrong metric."""
    headline = None
    for m in _TOKEN_RE.finditer(text):
        name, key, num = m.groups()
        if name is not None:
            headline = name
            continue
        if key == "value":
            if headline is not None:
                out[headline] = float(num)
                headline = None  # one headline, one value
            continue
        if key in _STRUCTURE_KEYS:
            continue
        out[key] = float(num)


def _flatten_numeric(obj, out: dict) -> None:
    """Collect numeric leaves of a nested dict keyed by their LEAF name
    (bench extras are flat and uniquely named; nested wrappers like the
    evidence file's rows just add structure)."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(v, bool):
                continue
            if isinstance(v, (int, float)):
                if isinstance(k, str) and k not in _STRUCTURE_KEYS:
                    out[str(k)] = float(v)
            elif isinstance(v, (dict, list)):
                _flatten_numeric(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _flatten_numeric(v, out)


def extract_metrics(source) -> dict[str, float]:
    """{metric name: value} from a bench artifact.

    Accepts: a BENCH record dict (``{n, cmd, rc, tail, parsed}``), a
    ``{"metric":..., "extras": {...}}`` headline dict, any nested dict of
    numbers (``BENCH_TPU_evidence.json``), raw bench stdout text, or a
    path to a JSON/text file holding any of those."""
    if isinstance(source, str):
        if os.path.exists(source):
            with open(source) as f:
                text = f.read()
            try:
                source = json.loads(text)
            except ValueError:
                source = text
        # fall through with text or the decoded object
    out: dict[str, float] = {}
    if isinstance(source, str):
        _scan_text(source, out)
        return out
    if isinstance(source, dict) and ("tail" in source or "parsed" in source):
        # BENCH record: tail first (truncated, older), parsed wins (complete)
        if isinstance(source.get("tail"), str):
            _scan_text(source["tail"], out)
        parsed = source.get("parsed")
        if isinstance(parsed, dict):
            _flatten_numeric(parsed.get("extras", {}), out)
            if isinstance(parsed.get("metric"), str) and \
                    isinstance(parsed.get("value"), (int, float)):
                out[parsed["metric"]] = float(parsed["value"])
        return out
    if isinstance(source, dict):
        if isinstance(source.get("metric"), str) and \
                isinstance(source.get("value"), (int, float)):
            out[source["metric"]] = float(source["value"])
        _flatten_numeric(source.get("extras", source), out)
        return out
    raise TypeError(f"cannot extract metrics from {type(source).__name__}")


# ---------------------------------------------------------------------------
# direction table
# ---------------------------------------------------------------------------

# (predicate order matters: first hit wins)
_NOT_A_METRIC = (
    "reference_", "_devices", "_batch", "batch", "_epochs", "epochs_",
    "_steps", "steps_per", "_seed", "_vocab", "_payload", "payload_",
    "_bytes", "_mb", "_requests", "n_requests", "_quantum", "_window",
    "_events", "_count", "capture_", "_buckets", "_replicas", "timed_",
    "warmup_", "_remat",
    # quant_sweep section: parity rows are correctness verdicts against a
    # stated tolerance (never perf-gated — a "regression" there is a test
    # failure, not a noise-band question), wire reductions and tolerances
    # are analytic constants. The grid's quant `_ms` cells stay gated
    # down-good via the `_ms` suffix rule below.
    "parity", "_reduction", "_tolerance",
    # serving_fleet section: worker/slot/chunk counts are configuration,
    # not measurements
    "_workers", "_slots", "_chunk",
    # paged_kv section: pool sizing and page geometry are configuration,
    # bit-identity is a verdict the contract test asserts (never a noise
    # band), peak-concurrent counts ride the gated concurrency RATIO, and
    # acceptance/window telemetry is workload-dependent
    "pages_at_budget", "page_size", "bit_identical", "_peak_concurrent",
    "capacity_tokens", "windows_used", "accept_rate", "ticks_per_token",
    # request_tracing section: verdict rows (`_ok` 0/1 flags), burn-rate
    # status/shares, tail attributions, and the per-class burst-schedule
    # accounting (requests/goodput/p99-threshold rows — tail stats over a
    # few dozen scripted requests, SLO accounting not a perf signal) are
    # never perf-gated; per_request_trace_us stays gated via the
    # "_trace_us" suffix and the tick walls via the "tick_ms" contains
    # rule
    "_ok", "dominant", "_burn", "tracing_interactive_", "tracing_batch_",
    # paged_attention section: the analytic HBM A/B rows are EXACT
    # program-structure counts (the "_bytes" rule above exempts them; a
    # changed count is a schedule change the contract test pins), the
    # live-shaped/table-shaped/parity/no-leak rows are `_ok` verdicts,
    # and eviction counts are workload constants via "_events". The
    # tick_p50_ms_live* walls gate down-good via the "tick_p50" contains
    # rule below, tp2_capacity_ratio up-good via "capacity_ratio", and
    # the preemption-vs-reservation throughput rows up-good via
    # "tokens_per_sec".
    # memory section: availability/provenance flags, device/watermark
    # counts, and the injected self-check's expectation constants are
    # structure, not perf (the residual/overhead rows gate through the
    # explicit memory rules in metric_direction below)
    "stats_available", "_watermarks", "memory_oom_", "expected_",
    # kernel_fusion section: the weight-byte compression ratios are
    # analytic codec constants the contract test pins against the
    # acceptance floors (a moved ratio is a codec change, not a
    # noise-band question), the MXU-idle fractions are analytic labels
    # (no rule matches them — ungated by default), and the provenance
    # rows are strings the flattener never sees. The
    # tick_p50_ms_live*_{single,pipelined} walls gate down-good via the
    # "tick_p50" contains rule and the ring_hop_ms_{fused,unfused}
    # walls via the "hop_ms" contains rule below (the _ms SUFFIX rule
    # misses the trailing schedule tag).
    "compression",
    # long_context section: ladder geometry + analytic accounting rows.
    # The KV wire-byte rows are EXACT schedule counts (the generic "_bytes"
    # rule above already exempts them — a changed count is a schedule
    # change the contract test pins, not a noise-band question) and the
    # _act_gb headroom table is analytic; rung `_ms` cells stay gated
    # down-good via the `_ms` suffix rule, `_mfu`/`max_tokens` up-good.
    "rungs_planned", "ladder_target", "keep_fraction", "_act_gb",
)
_HIGHER_BETTER = (
    "samples_per_sec", "tokens_per_sec", "tokens_per_s", "goodput",
    "accuracy", "mfu", "speedup", "coverage_pct",
    # paged_kv: concurrent-sequence capacity per HBM byte — the headline
    "capacity_ratio", "concurrency_ratio",
    # long_context: the highest sequence rung a train step COMPLETED
    "max_tokens",
)
_LOWER_BETTER_SUFFIX = ("_ms", "_s", "_sec", "_trace_us", "_pct", "_ppl")
# "_trace_us" (not bare "_us"): gates request_tracing's per-request bill
# down-good WITHOUT flipping forensics_enabled_bundle_us — a single-shot
# µs wall sample that was deliberately never gated.
# "ttft"/"tpot": the serving_fleet section's time-to-first-token and
# per-token-latency rows gate down-good (their `_ms` suffix already says
# so; the explicit tokens make the intent survive a unit rename), while
# `goodput_per_chip`/`tokens_per_sec` ride the up-good table above and
# `burst_isolation_speedup` the "speedup" rule.
_LOWER_BETTER_CONTAINS = ("loss", "overhead", "stall", "latency", "ttft",
                          # "tick_ms": the request_tracing fleet tick
                          # walls end in _enabled/_disabled, so the _ms
                          # SUFFIX rule misses them — the enabled-vs-
                          # disabled A/B is the end-to-end cost this
                          # section exists to watch
                          "tpot", "tick_ms",
                          # "tick_p50": the paged_attention section's
                          # per-live-fraction decode-tick walls
                          # (tick_p50_ms_live25/...): the _ms SUFFIX rule
                          # misses the trailing fraction tag
                          "tick_p50",
                          # "hop_ms": the kernel_fusion section's per-hop
                          # ring walls (ring_hop_ms_fused/_unfused): the
                          # _ms SUFFIX rule misses the trailing schedule
                          # tag
                          "hop_ms")


# memory-ledger rows (ISSUE 15): peak-byte watermarks and the
# unattributed residual gate DOWN-GOOD even though the generic "_bytes"
# rule above exempts byte rows (those are analytic schedule counts; a
# PEAK is a measurement — more resident bytes at the same workload is a
# memory regression exactly like a slower step is a latency regression).
# Capacity/provenance rows stay ungated: bytes_limit is the chip, not
# the code, and the claimed-taxonomy rows are attribution bookkeeping
# whose "regressions" are the contract test's business.
_MEMORY_NEVER_GATED = ("bytes_limit", "claimed_", "hbm_source")
# "unattributed_bytes"/"_gb", not bare "unattributed": the fleet-merge
# structure row memory_fleet_unattributed_rows is a process COUNT
_MEMORY_DOWN_GOOD = ("peak_bytes", "peak_gb", "unattributed_bytes",
                     "unattributed_gb")


def metric_direction(name: str) -> str | None:
    """"higher" / "lower" = which way is GOOD; None = not a perf metric
    (config constants, provenance counts) — never gated."""
    low = name.lower()
    if any(t in low for t in _MEMORY_NEVER_GATED):
        return None
    if any(t in low for t in _MEMORY_DOWN_GOOD):
        return "lower"
    if any(t in low for t in _NOT_A_METRIC):
        return None
    if any(t in low for t in _HIGHER_BETTER):
        return "higher"
    if any(t in low for t in _LOWER_BETTER_CONTAINS):
        return "lower"
    if low.endswith(_LOWER_BETTER_SUFFIX):
        return "lower"
    return None


# ---------------------------------------------------------------------------
# noise bands + comparison
# ---------------------------------------------------------------------------


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def noise_band(history: list[float], k: float = DEFAULT_K,
               rel_floor: float = DEFAULT_REL_FLOOR) -> dict:
    """median ± max(k·MAD, rel_floor·|median|) — MAD is robust to the
    history's outlier rounds (a dead-tunnel CPU fallback must not drag
    the center), the relative floor keeps an all-identical history from
    flagging any measurement jitter as a regression."""
    med = _median(history)
    mad = _median([abs(v - med) for v in history])
    half = max(k * mad, rel_floor * abs(med))
    return {
        "median": med, "mad": mad, "half_width": half,
        "lo": med - half, "hi": med + half, "n": len(history),
        "noise_ratio": (mad / abs(med)) if med else None,
    }


def compare(fresh: dict[str, float], history: list[dict[str, float]],
            k: float = DEFAULT_K, rel_floor: float = DEFAULT_REL_FLOOR,
            min_history: int = DEFAULT_MIN_HISTORY) -> dict:
    """Gate ``fresh`` against per-metric noise bands over ``history``.

    Per metric: ``regression`` (fresh beyond the band on the BAD side),
    ``improved`` (beyond on the good side), ``ok`` (inside),
    ``insufficient_history`` (< min_history samples), ``too_noisy``
    (MAD/|median| > NOISE_CEILING — no signal), ``not_gated`` (no
    direction). The report is the artifact; ``regressions`` is the exit
    verdict."""
    rows: dict[str, dict] = {}
    regressions: list[str] = []
    for name in sorted(fresh):
        value = fresh[name]
        samples = [h[name] for h in history if name in h]
        direction = metric_direction(name)
        row: dict = {"fresh": value, "direction": direction,
                     "n_history": len(samples)}
        if direction is None:
            row["status"] = "not_gated"
        elif len(samples) < min_history:
            row["status"] = "insufficient_history"
        else:
            band = noise_band(samples, k=k, rel_floor=rel_floor)
            row.update(band)
            ratio = band["noise_ratio"]
            if ratio is not None and ratio > NOISE_CEILING:
                row["status"] = "too_noisy"
            elif direction == "higher" and value < band["lo"]:
                row["status"] = "regression"
            elif direction == "lower" and value > band["hi"]:
                row["status"] = "regression"
            elif direction == "higher" and value > band["hi"]:
                row["status"] = "improved"
            elif direction == "lower" and value < band["lo"]:
                row["status"] = "improved"
            else:
                row["status"] = "ok"
        if row["status"] == "regression":
            regressions.append(name)
        rows[name] = row
    counts: dict[str, int] = {}
    for row in rows.values():
        counts[row["status"]] = counts.get(row["status"], 0) + 1
    return {
        "schema": REPORT_SCHEMA,
        "params": {"k": k, "rel_floor": rel_floor,
                   "min_history": min_history,
                   "noise_ceiling": NOISE_CEILING},
        "n_history_records": len(history),
        "metrics": rows,
        "counts": counts,
        "regressions": regressions,
    }


# ---------------------------------------------------------------------------
# calibrated collective-latency profile (cost-model planner input)
# ---------------------------------------------------------------------------

# bench keys that ARE calibration constants for the planner's cost model
_PROFILE_PREFIXES = ("allreduce_", "bucket_sweep_", "v8_")
_PROFILE_EXACT = ("serving_host_rtt_ms",)
_PROFILE_SUFFIXES = ("_step_ms",)


def _is_profile_key(name: str) -> bool:
    return (name.startswith(_PROFILE_PREFIXES)
            or name in _PROFILE_EXACT
            or name.endswith(_PROFILE_SUFFIXES))


def export_profile(fresh: dict[str, float],
                   history: list[dict[str, float]]) -> dict:
    """The measured collective/step-time constants, centered by history
    median (robust to outlier rounds) with the fresh sample alongside —
    the calibration input the ROADMAP's auto-parallel planner consumes
    instead of re-measuring."""
    constants: dict[str, dict] = {}
    names = {n for n in fresh if _is_profile_key(n)}
    for h in history:
        names.update(n for n in h if _is_profile_key(n))
    for name in sorted(names):
        samples = [h[name] for h in history if name in h]
        entry: dict = {}
        if name in fresh:
            entry["fresh"] = fresh[name]
        if samples:
            entry["median"] = _median(samples)
            entry["mad"] = _median(
                [abs(v - entry["median"]) for v in samples]
            )
            entry["n"] = len(samples)
        constants[name] = entry
    derived: dict[str, float] = {}
    ring = constants.get("allreduce_ring_p50_ms", {}).get("median")
    payload = constants.get("allreduce_payload_mb", {}).get("median")
    e2e = constants.get("allreduce_e2e_p50_ms", {}).get("median")
    if ring is not None and payload:
        derived["ring_ms_per_mb"] = ring / payload
    if e2e is not None and ring is not None:
        # wire-path fixed cost: gRPC hops + host staging beyond the
        # on-mesh reduction itself
        derived["wire_overhead_ms"] = max(e2e - ring, 0.0)
    return {"schema": PROFILE_SCHEMA, "constants": constants,
            "derived": derived}


def profile_from_merged(merged) -> dict:
    """Calibration constants from an AGGREGATED cluster view's
    ``collective_latency_ms{algorithm,axis}`` fleet histograms — the
    cross-process measurement path (ISSUE: the cost model "must be
    calibrated from aggregated measured collective-latency histograms")."""
    from dsml_tpu.obs.cluster import estimate_quantile

    constants: dict[str, dict] = {}
    for rec in merged.collect():
        if rec["name"] != "collective_latency_ms:fleet":
            continue
        labels = rec["labels"]
        bounds = tuple(b for b in rec["buckets"] if b != "+Inf")
        key = "collective_{algorithm}_{axis}".format(
            algorithm=labels.get("algorithm", "unknown"),
            axis=labels.get("axis", "unknown"),
        )
        constants[key] = {
            "count": rec["count"],
            "mean_ms": (rec["sum"] / rec["count"]) if rec["count"] else None,
            "p50_ms": estimate_quantile(bounds, rec["buckets"], 0.5),
            "p90_ms": estimate_quantile(bounds, rec["buckets"], 0.9),
        }
    return {"schema": PROFILE_SCHEMA, "constants": constants, "derived": {}}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _load_history(patterns: list[str]) -> tuple[list[str], list[dict]]:
    paths: list[str] = []
    for pat in patterns:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else ([pat] if os.path.exists(pat) else []))
    records = []
    used = []
    for p in paths:
        metrics = extract_metrics(p)
        if metrics:
            records.append(metrics)
            used.append(p)
    return used, records


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m dsml_tpu.obs.regress",
        description="compare a fresh bench record against the BENCH_r*.json "
        "history with per-metric noise bands; exit 1 on regression",
    )
    ap.add_argument("--fresh", default=None,
                    help="fresh bench artifact (JSON record or raw stdout); "
                    "default: the newest history file (self-check mode)")
    ap.add_argument("--history", nargs="*", default=["BENCH_r*.json"],
                    help="history files/globs (default: BENCH_r*.json)")
    ap.add_argument("--k", type=float, default=DEFAULT_K,
                    help=f"band half-width in MADs (default {DEFAULT_K})")
    ap.add_argument("--rel-floor", type=float, default=DEFAULT_REL_FLOOR,
                    help="minimum band half-width as a fraction of |median| "
                    f"(default {DEFAULT_REL_FLOOR})")
    ap.add_argument("--min-history", type=int, default=DEFAULT_MIN_HISTORY,
                    help="samples required before a metric is gated "
                    f"(default {DEFAULT_MIN_HISTORY})")
    ap.add_argument("--report", default=None,
                    help="write the full comparison report JSON here")
    ap.add_argument("--profile", default=None,
                    help="write the calibrated collective-latency profile "
                    "JSON here (cost-model planner input)")
    ap.add_argument("--report-only", action="store_true",
                    help="always exit 0 (CI advisory mode); the report still "
                    "records the verdict")
    args = ap.parse_args(argv)

    used, history = _load_history(args.history)
    if not history:
        print(f"regress: no parseable history from {args.history}")
        return 2
    if args.fresh is not None:
        fresh = extract_metrics(args.fresh)
        fresh_src = args.fresh
    else:
        fresh = history[-1]
        fresh_src = used[-1] + " (self-check)"
    if not fresh:
        print(f"regress: nothing parseable in fresh artifact {fresh_src}")
        return 2

    report = compare(fresh, history, k=args.k, rel_floor=args.rel_floor,
                     min_history=args.min_history)
    report["fresh_source"] = fresh_src
    report["history_sources"] = used
    report["report_only"] = bool(args.report_only)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    if args.profile:
        with open(args.profile, "w") as f:
            json.dump(export_profile(fresh, history), f, indent=2,
                      sort_keys=True)

    counts = report["counts"]
    print(f"regress: {len(fresh)} fresh metrics vs {len(history)} history "
          f"records ({used[0]}..{used[-1]}): "
          + ", ".join(f"{k}={v}" for k, v in sorted(counts.items())))
    for name in report["regressions"]:
        row = report["metrics"][name]
        print(f"  REGRESSION {name}: fresh={row['fresh']:g} outside "
              f"[{row['lo']:g}, {row['hi']:g}] (median={row['median']:g}, "
              f"direction={row['direction']})")
    if report["regressions"] and not args.report_only:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
