"""Per-SLOClass SLI windows, error budgets, burn rates, tail attribution.

The serving router (PR 10) admits by SLO class, but a class's budget was
an admission-time heuristic with no measured compliance: nothing answered
"is the interactive class MEETING its TTFT objective, and how fast is it
spending its error budget?". This module is the measured half — the
SRE-workbook shape (multi-window burn-rate alerting) over the fleet's
own request samples:

- **SLIs**: per class, each configured budget (TTFT / TPOT / e2e) is a
  binary good/bad verdict per retired request; compliance over a rolling
  window is the SLI.
- **Error budget + burn rate**: with objective ``o`` (e.g. 0.99), the
  budget is the allowed bad fraction ``1-o``; ``burn_rate = bad_frac /
  (1-o)`` — 1.0 burns exactly the budget over the window, 14.4 exhausts
  a 30-day budget in ~2 days. Two windows (fast ~1 min, slow ~10 min by
  default here; production uses 5 m/1 h) gate the status: both above the
  page threshold ⇒ ``page``, both above the warn threshold ⇒ ``warn``,
  else ``ok`` — the multi-window rule that suppresses blips (fast-only)
  and stale alerts (slow-only).
- **Goodput**: requests meeting EVERY configured budget, counted per
  class — the scheduler-facing "useful completions" number the ROADMAP's
  multi-job fleet controller wants per tenant.
- **Tail attribution**: per-request stage breakdown (queue, prefill,
  handoff, first-decode, inter-token) aggregated over the e2e tail —
  which STAGE dominates each class's p99, with the worst request's
  trace_id as the exemplar.

Everything exports into the metrics registry (`slo_*` series, merged
fleet-wide by ``obs.cluster.MergedView`` — ``report()`` carries the
per-class fleet burn status), and the pure math (:func:`burn_rate`,
:func:`window_compliance`, :func:`tail_attribution`) is numpy-pinned in
``tests/test_request_tracing.py``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from dsml_tpu.obs.registry import Registry, get_registry

__all__ = [
    "SLIS",
    "SLOSpec",
    "SLOTracker",
    "STAGES",
    "burn_rate",
    "status_from_burn",
    "tail_attribution",
    "window_compliance",
]

# the three request-latency SLIs a serving class can budget
SLIS = ("ttft", "tpot", "e2e")

# per-request stage breakdown (seconds), in causal order; "decode" is the
# inter-token phase after the first token
STAGES = ("queue", "prefill", "handoff", "first_decode", "decode")

# burn-rate thresholds (SRE workbook defaults): both windows above PAGE
# pages, both above WARN warns. A burn of 1.0 spends exactly the budget.
PAGE_BURN = 14.4
WARN_BURN = 6.0

# bounded per-class sample memory (the stage/tail attribution source)
_STAGE_SAMPLE_CAP = 4096

# numeric encoding of the status ladder, exported as a gauge so the
# cluster merge can take a fleet-wide max (strings don't merge)
STATUS_LEVELS = {"ok": 0, "warn": 1, "page": 2}


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One class's objectives. A ``None`` budget means that SLI is not
    part of this class's contract (batch traffic rarely budgets TTFT).
    ``objective`` is the target good fraction shared by every budgeted
    SLI — 0.99 allows 1% of requests over budget before the burn rate
    exceeds 1."""

    name: str
    objective: float = 0.99
    ttft_budget_ms: float | None = None
    tpot_budget_ms: float | None = None
    e2e_budget_ms: float | None = None
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective} "
                f"(class {self.name!r})"
            )
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                f"need 0 < fast_window_s <= slow_window_s, got "
                f"{self.fast_window_s}/{self.slow_window_s}"
            )

    def budget_ms(self, sli: str) -> float | None:
        return {"ttft": self.ttft_budget_ms, "tpot": self.tpot_budget_ms,
                "e2e": self.e2e_budget_ms}[sli]

    def budgeted_slis(self) -> tuple:
        return tuple(s for s in SLIS if self.budget_ms(s) is not None)


def window_compliance(events, now: float, window_s: float) -> tuple[int, int]:
    """(good, total) over events ``(t, good)`` with ``t > now - window_s``.
    Plain counting — the numpy pin in tests re-derives it independently."""
    lo = now - window_s
    good = total = 0
    for t, ok in events:
        if t > lo:
            total += 1
            good += 1 if ok else 0
    return good, total


def burn_rate(bad_fraction: float, objective: float) -> float:
    """How fast the error budget is being spent: observed bad fraction
    over the allowed bad fraction ``1 - objective``. 0 when nothing is
    bad; 1.0 = spending exactly the budget; `1/(1-o)` when EVERYTHING
    is bad (the ceiling — at o=0.99 that is 100)."""
    allowed = 1.0 - objective
    if allowed <= 0.0:
        raise ValueError(f"objective {objective} leaves no error budget")
    return bad_fraction / allowed


def status_from_burn(fast: float, slow: float,
                     page: float = PAGE_BURN, warn: float = WARN_BURN) -> str:
    """The multi-window rule: BOTH windows must agree before escalating —
    a fast-only spike is a blip, a slow-only excess is an already-ended
    incident still draining out of the long window."""
    if fast >= page and slow >= page:
        return "page"
    if fast >= warn and slow >= warn:
        return "warn"
    return "ok"


def tail_attribution(samples, q: float = 0.99) -> dict | None:
    """Attribute a latency tail to its dominant stage.

    ``samples``: list of ``(e2e_s, stages_dict, trace_id)`` — the tracker
    keeps one bounded deque per class. Requests at or above the ``q``
    quantile of e2e form the tail set; their mean per-stage seconds name
    the ``dominant_stage``, and the single worst request's trace_id rides
    along as the exemplar (the "open THIS trace" link)."""
    if not samples:
        return None
    e2e = sorted(s[0] for s in samples)
    # nearest-rank quantile (matches numpy 'higher' within one sample —
    # the tests pin the tail SET, not an interpolated scalar)
    idx = min(int(q * len(e2e)), len(e2e) - 1)
    threshold = e2e[idx]
    tail = [s for s in samples if s[0] >= threshold]
    worst = max(tail, key=lambda s: s[0])
    stage_ms = {}
    for stage in STAGES:
        vals = [s[1].get(stage) for s in tail]
        vals = [v for v in vals if v is not None]
        if vals:
            stage_ms[stage] = round(sum(vals) / len(vals) * 1e3, 3)
    if not stage_ms:
        return None
    dominant = max(stage_ms, key=stage_ms.get)
    return {
        "p_quantile": q,
        "threshold_ms": round(threshold * 1e3, 3),
        "n_tail": len(tail),
        "n_samples": len(samples),
        "stage_ms": stage_ms,
        "dominant_stage": dominant,
        "dominant_share": round(
            stage_ms[dominant] / max(sum(stage_ms.values()), 1e-12), 4
        ),
        "worst_e2e_ms": round(worst[0] * 1e3, 3),
        "worst_trace_id": worst[2],
    }


# per-window event retention cap: a window's compliance is computed over
# at most this many most-recent events — bounds memory at any QPS (the
# rolling counts stay O(1) per record either way)
_SLI_EVENT_CAP = 8192


class _Window:
    """One rolling SLI window with O(1)-amortized incremental counts —
    ``SLOTracker.record`` runs on the serving harvest path, so compliance
    must never rescan the event history per request."""

    __slots__ = ("window_s", "events", "good")

    def __init__(self, window_s: float):
        self.window_s = window_s
        self.events: deque = deque(maxlen=_SLI_EVENT_CAP)
        self.good = 0

    def add(self, t: float, ok: bool) -> None:
        if len(self.events) == self.events.maxlen:
            _, old_ok = self.events[0]  # maxlen evicts silently — account
            self.good -= 1 if old_ok else 0
        self.events.append((t, ok))
        self.good += 1 if ok else 0
        self.prune(t)

    def prune(self, now: float) -> None:
        lo = now - self.window_s
        ev = self.events
        while ev and ev[0][0] <= lo:
            _, ok = ev.popleft()
            self.good -= 1 if ok else 0

    def counts(self, now: float) -> tuple[int, int]:
        self.prune(now)
        return self.good, len(self.events)


class _SLIState:
    __slots__ = ("fast", "slow", "good_total", "total")

    def __init__(self, spec: "SLOSpec"):
        self.fast = _Window(spec.fast_window_s)
        self.slow = _Window(spec.slow_window_s)
        self.good_total = 0           # all-time (the fleet-merge counters)
        self.total = 0


class SLOTracker:
    """Measured SLO compliance per class, fed one retired request at a
    time (:meth:`record`). Windows use the caller's clock (default
    ``time.monotonic`` — the same origin as the serving timing marks).

    Registry export (when observability is enabled): ``slo_requests_total
    {slo}``, ``slo_good_total{slo}`` (goodput: every budgeted SLI met),
    ``slo_sli_total{slo,sli,verdict}`` (the exact fleet-mergeable
    counters), ``slo_objective{slo}``, ``slo_burn_rate{slo,sli,window}``
    and ``slo_burn_status{slo,sli}`` (0 ok / 1 warn / 2 page) gauges —
    docs/OBSERVABILITY.md § Request tracing & SLO budgets."""

    def __init__(self, specs, registry: Registry | None = None, clock=None):
        specs = list(specs)
        if not specs:
            raise ValueError("SLOTracker needs at least one SLOSpec")
        self.specs = {s.name: s for s in specs}
        if len(self.specs) != len(specs):
            raise ValueError("duplicate SLO class names")
        self._clock = clock if clock is not None else time.monotonic
        # RLock: record() holds it across _export → export_gauges, and the
        # registry's scrape-time collect hook refreshes gauges from OTHER
        # threads (the HTTP metrics server) — window counts() prunes, so
        # unsynchronized concurrent reads would corrupt the running good
        # counter
        self._lock = threading.RLock()
        self._obs = registry if registry is not None else get_registry()
        self._sli: dict[tuple, _SLIState] = {
            (s.name, sli): _SLIState(s)
            for s in specs for sli in s.budgeted_slis()
        }
        # (class, sli) -> budget ms, flattened once: spec.budget_ms builds
        # a dict per call and record() runs per retired request
        self._budgets: dict[str, tuple] = {
            s.name: tuple((sli, s.budget_ms(sli))
                          for sli in s.budgeted_slis())
            for s in specs
        }
        # burn-rate GAUGES recompute at most ~4x/s per class (counters
        # still bump per record — they must merge exactly); the first
        # record always exports so tests/short runs see the series
        self._last_gauge_export: dict[str, float] = {}
        # metric handles resolved ONCE — record() runs per retired request
        # on the router's harvest path, and the registry's get-or-create
        # lookup is not free there
        reg = self._obs
        c_requests = reg.counter(
            "slo_requests_total", "retired requests per SLO class",
            labels=("slo",),
        )
        c_good = reg.counter(
            "slo_good_total",
            "requests that met every budgeted SLI (goodput)", labels=("slo",),
        )
        c_sli = reg.counter(
            "slo_sli_total",
            "per-SLI request verdicts (exact fleet-mergeable counts)",
            labels=("slo", "sli", "verdict"),
        )
        # bound series per (class, sli, verdict): label validation paid at
        # init, one lock per inc on the harvest path
        self._b_requests = {s: c_requests.bind(slo=s) for s in self.specs}
        self._b_good = {s: c_good.bind(slo=s) for s in self.specs}
        self._b_sli = {
            (s.name, sli, verdict): c_sli.bind(slo=s.name, sli=sli,
                                               verdict=verdict)
            for s in specs for sli in s.budgeted_slis()
            for verdict in ("good", "bad")
        }
        self._g_objective = reg.gauge(
            "slo_objective", "target good fraction per class", labels=("slo",),
        )
        self._g_burn = reg.gauge(
            "slo_burn_rate",
            "error-budget burn rate over the rolling window",
            labels=("slo", "sli", "window"),
        )
        self._g_status = reg.gauge(
            "slo_burn_status",
            "multi-window burn status (0 ok / 1 warn / 2 page)",
            labels=("slo", "sli"),
        )
        self.requests: dict[str, int] = {s.name: 0 for s in specs}
        self.good_requests: dict[str, int] = {s.name: 0 for s in specs}
        self._stage_samples: dict[str, deque] = {
            s.name: deque(maxlen=_STAGE_SAMPLE_CAP) for s in specs
        }
        # scrape-time refresh: the burn gauges depend on the CLOCK (rolling
        # windows drain), not just on ingest — without this hook a gauge
        # last exported mid-burst would freeze at "page" once the class's
        # traffic stops, and every exposition/snapshot/MergedView would
        # report a permanently-firing alert on an idle class. Weakly held:
        # dies with the tracker.
        reg.add_collect_hook(self.export_gauges)

    # -- ingest ------------------------------------------------------------

    def record(self, name: str, ttft_ms: float | None = None,
               tpot_ms: float | None = None, e2e_ms: float | None = None,
               trace_id: str | None = None,
               stages: dict | None = None) -> dict:
        """One retired request's measured latencies → SLI verdicts.

        A budgeted SLI with a ``None`` measurement is NOT MEASURABLE for
        this request and is skipped — it counts toward neither window
        (TPOT is undefined for a single-token request; counting it as
        bad would burn a class's TPOT budget on traffic that fully met
        its contract). Requests that never produce a first token never
        reach the router's harvest, so None here always means
        "inapplicable", not "failed". Returns {sli: good} for the
        class's MEASURED budgeted SLIs."""
        spec = self.specs.get(name)
        if spec is None:
            raise ValueError(
                f"unknown SLO class {name!r}; declared: {sorted(self.specs)}"
            )
        now = self._clock()
        measured = {"ttft": ttft_ms, "tpot": tpot_ms, "e2e": e2e_ms}
        verdicts: dict[str, bool] = {}
        with self._lock:
            for sli, budget in self._budgets[name]:
                val = measured[sli]
                if val is None:
                    continue
                good = val <= budget
                verdicts[sli] = good
                state = self._sli[(name, sli)]
                state.fast.add(now, good)
                state.slow.add(now, good)
                state.total += 1
                state.good_total += 1 if good else 0
            self.requests[name] += 1
            all_good = all(verdicts.values()) if verdicts else True
            if all_good:
                self.good_requests[name] += 1
            if stages is not None and e2e_ms is not None:
                self._stage_samples[name].append(
                    (e2e_ms / 1e3, dict(stages), trace_id)
                )
            self._export(name, spec, verdicts, all_good)
        return verdicts

    def reset(self) -> None:
        """Drop every rolling window, per-class counter, and stage
        sample — warm-up isolation (bench legs drive jit-compiling
        requests through the fleet before the measured schedule; their
        seconds-long e2e would own the p99 tail and the burn windows).
        The registry's ``slo_*`` counters are monotonic by contract
        (fleet merges sum them exactly) and are NOT rolled back."""
        with self._lock:
            for state in self._sli.values():
                for w in (state.fast, state.slow):
                    w.events.clear()
                    w.good = 0
                state.good_total = 0
                state.total = 0
            for name in self.requests:
                self.requests[name] = 0
                self.good_requests[name] = 0
            for dq in self._stage_samples.values():
                dq.clear()
            self._last_gauge_export.clear()

    # -- derived -----------------------------------------------------------

    def burn(self, name: str, sli: str, window: str = "fast",
             now: float | None = None) -> dict:
        """{good, total, compliance, burn} over the ``"fast"`` or
        ``"slow"`` rolling window (O(1) — incremental counts). Zero
        traffic in the window burns nothing (burn 0, compliance None)."""
        spec = self.specs[name]
        state = self._sli[(name, sli)]
        now = self._clock() if now is None else now
        with self._lock:  # counts() PRUNES; scrape hooks read concurrently
            good, total = getattr(state, window).counts(now)
        if total == 0:
            return {"good": 0, "total": 0, "compliance": None, "burn": 0.0}
        bad_frac = (total - good) / total
        return {
            "good": good, "total": total,
            "compliance": good / total,
            "burn": burn_rate(bad_frac, spec.objective),
        }

    def status(self, name: str, sli: str) -> dict:
        now = self._clock()
        fast = self.burn(name, sli, "fast", now)
        slow = self.burn(name, sli, "slow", now)
        # the burn CEILING is 1/(1-objective) (everything bad): at loose
        # objectives the standard thresholds would be unreachable — a
        # class burning its ENTIRE budget must page regardless, so the
        # thresholds clamp to the achievable range
        ceiling = burn_rate(1.0, self.specs[name].objective)
        return {
            "fast": fast, "slow": slow,
            "status": status_from_burn(
                fast["burn"], slow["burn"],
                page=min(PAGE_BURN, ceiling),
                warn=min(WARN_BURN, ceiling / 2.0),
            ),
        }

    def tail_attribution(self, name: str, q: float = 0.99) -> dict | None:
        with self._lock:
            samples = list(self._stage_samples[name])
        return tail_attribution(samples, q=q)

    def report(self) -> dict:
        """Per-class machine-readable summary — the bench/CI artifact and
        the shape ``MergedView.report()`` mirrors fleet-wide."""
        out: dict = {}
        for name, spec in self.specs.items():
            row: dict = {
                "objective": spec.objective,
                "requests": self.requests[name],
                "good_requests": self.good_requests[name],
                "sli": {},
            }
            worst = "ok"
            for sli in spec.budgeted_slis():
                st = self.status(name, sli)
                state = self._sli[(name, sli)]
                row["sli"][sli] = {
                    "budget_ms": spec.budget_ms(sli),
                    "good_total": state.good_total,
                    "total": state.total,
                    "fast_burn": round(st["fast"]["burn"], 4),
                    "slow_burn": round(st["slow"]["burn"], 4),
                    "status": st["status"],
                }
                if STATUS_LEVELS[st["status"]] > STATUS_LEVELS[worst]:
                    worst = st["status"]
            row["status"] = worst
            tail = self.tail_attribution(name)
            if tail is not None:
                row["tail"] = tail
            out[name] = row
        return out

    # -- registry export ---------------------------------------------------

    def _export(self, name: str, spec: SLOSpec, verdicts: dict,
                all_good: bool) -> None:
        if not self._obs.enabled:
            return
        self._b_requests[name].inc()
        if all_good:
            self._b_good[name].inc()
        for sli, good in verdicts.items():
            self._b_sli[(name, sli, "good" if good else "bad")].inc()
        # gauges recompute at most ~4x/s per class on the harvest path;
        # scrapes force a fresh export via the registry collect hook
        self.export_gauges(name)

    def export_gauges(self, name: str | None = None,
                      force: bool = False) -> None:
        """Recompute the burn-rate/status gauges for ``name`` (or every
        class). Throttled to ~4x/s per class — the harvest path must not
        pay a full status recompute per retired request — and refreshed
        by every exposition via the registry collect hook (same
        throttle): the rolling windows drain with the CLOCK, so a gauge
        is stale the moment traffic stops, not just when a record is
        missed; a scrape sees status at most 250 ms old instead of
        frozen-at-last-burst forever."""
        if not self._obs.enabled:
            return
        now = self._clock()
        for cls in ((name,) if name is not None else tuple(self.specs)):
            spec = self.specs[cls]
            last = self._last_gauge_export.get(cls)
            if not force and last is not None and now - last < 0.25:
                continue
            self._last_gauge_export[cls] = now
            self._g_objective.set(spec.objective, slo=cls)
            for sli in spec.budgeted_slis():
                st = self.status(cls, sli)
                for window, b in (("fast", st["fast"]), ("slow", st["slow"])):
                    self._g_burn.set(round(b["burn"], 6), slo=cls, sli=sli,
                                     window=window)
                self._g_status.set(STATUS_LEVELS[st["status"]],
                                   slo=cls, sli=sli)

