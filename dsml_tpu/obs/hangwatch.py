"""Hang/straggler detection: a deadline watchdog for the paths that wedge.

A collective that never completes doesn't crash — it sits. The reference
coordinator's health loop catches DEAD devices (probe timeout) but a
wedged-yet-alive one keeps answering probes while the training step
blocks forever. This module is the missing deadline layer:

- :class:`HangWatch` — one daemon watchdog thread per instance; callers
  **arm** a named deadline around a blocking operation and **disarm** it
  on completion. On expiry the watchdog dumps all-thread Python stacks
  plus a full flight-recorder postmortem bundle (reason ``hang_<name>``),
  increments ``hang_suspected_total{watcher}``, and logs the armed
  context. Expiry fires ONCE per armed token — a genuinely hung process
  leaves exactly one bundle, then the operator's stack dump shows where.
- :class:`TrailingDeadline` — turns observed durations into a deadline:
  ``k × trailing-median`` with a floor, ``None`` until enough samples
  exist (compile-skewed first steps must not set the bar).

Wired call sites: the trainer arms per loss-sync window (k×
trailing-median window wall — the only point its loop truly blocks under
async dispatch), the coordinator arms per wire op, the async checkpoint
writer per commit. All of it is off unless ``DSML_HANGWATCH`` is set: ``1`` enables
the default multiplier (10×), a number sets the multiplier itself.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import statistics
import threading
import time

from dsml_tpu.obs import flight_recorder
from dsml_tpu.obs.registry import Registry, get_registry
from dsml_tpu.utils.logging import get_logger

__all__ = [
    "HangWatch",
    "TrailingDeadline",
    "HangWatchConfig",
    "get_hangwatch",
    "config_from_env",
]

log = get_logger("hangwatch")

DEFAULT_MULTIPLIER = 10.0


@dataclasses.dataclass(frozen=True)
class HangWatchConfig:
    multiplier: float = DEFAULT_MULTIPLIER  # deadline = multiplier × median
    floor_s: float = 1.0                    # never arm tighter than this
    min_samples: int = 5                    # observations before arming


def config_from_env(spec: str | None = None) -> HangWatchConfig | None:
    """``DSML_HANGWATCH``: unset/``0`` → ``None`` (off); ``1`` → default
    10× multiplier; a number → that multiplier."""
    if spec is None:
        spec = os.environ.get("DSML_HANGWATCH", "")
    spec = spec.strip().lower()
    if spec in ("", "0", "false", "off"):
        return None
    if spec in ("1", "true", "on"):
        return HangWatchConfig()
    try:
        mult = float(spec)
    except ValueError as e:
        raise ValueError(
            f"DSML_HANGWATCH={spec!r} is neither a flag nor a multiplier"
        ) from e
    if mult <= 0:
        raise ValueError(f"DSML_HANGWATCH multiplier must be positive, got {mult}")
    return HangWatchConfig(multiplier=mult)


class TrailingDeadline:
    """k × trailing-median duration, floored; ``None`` until warmed up."""

    def __init__(self, multiplier: float = DEFAULT_MULTIPLIER,
                 floor_s: float = 1.0, window: int = 64, min_samples: int = 5):
        self.multiplier = float(multiplier)
        self.floor_s = float(floor_s)
        self.min_samples = max(int(min_samples), 1)
        self._lock = threading.Lock()
        self._walls: collections.deque = collections.deque(maxlen=window)

    @classmethod
    def from_config(cls, cfg: HangWatchConfig, floor_s: float | None = None,
                    window: int = 64) -> "TrailingDeadline":
        return cls(multiplier=cfg.multiplier,
                   floor_s=cfg.floor_s if floor_s is None else floor_s,
                   window=window, min_samples=cfg.min_samples)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._walls.append(float(seconds))

    def timeout_s(self) -> float | None:
        with self._lock:
            if len(self._walls) < self.min_samples:
                return None
            median = statistics.median(self._walls)
        return max(self.multiplier * median, self.floor_s)


class _Armed:
    __slots__ = ("token", "name", "deadline", "timeout_s", "info", "thread")

    def __init__(self, token, name, deadline, timeout_s, info, thread):
        self.token = token
        self.name = name
        self.deadline = deadline
        self.timeout_s = timeout_s
        self.info = info
        self.thread = thread


class HangWatch:
    """Armable-deadline watchdog; the worker thread starts lazily on the
    first :meth:`arm` and sleeps on a condition between deadlines."""

    def __init__(self, registry: Registry | None = None,
                 recorder: "flight_recorder.FlightRecorder | None" = None,
                 clock=time.monotonic, name: str = "hangwatch"):
        self.registry = registry if registry is not None else get_registry()
        self.recorder = (recorder if recorder is not None
                         else flight_recorder.get_flight_recorder())
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._armed: dict[int, _Armed] = {}
        self._tokens = itertools.count(1)
        self._thread: threading.Thread | None = None
        self._next_wake: float | None = None  # when the worker will look next
        self._closed = False
        self.fired: list[dict] = []

    def arm(self, name: str, timeout_s: float, **info) -> int:
        """Start a deadline; returns a token for :meth:`disarm`. The armed
        record remembers the calling thread so the expiry dump can point
        at the stack that is actually stuck."""
        timeout_s = float(timeout_s)
        with self._lock:
            if self._closed:
                raise RuntimeError(f"{self._name} is closed")
            token = next(self._tokens)
            deadline = self._clock() + timeout_s
            self._armed[token] = _Armed(
                token, name, deadline, timeout_s, info,
                threading.current_thread().name,
            )
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            # wake the worker ONLY when this deadline lands before its next
            # scheduled look — the hot arm/disarm-per-step path must not pay
            # a futex wake + context switch per call
            if self._next_wake is None or deadline < self._next_wake:
                self._wake.notify_all()
        return token

    def disarm(self, token: int) -> None:
        """Cancel an armed deadline (completing after expiry is fine — the
        token is already gone and this is a no-op). Never wakes the worker:
        a stale scheduled look finds nothing expired and goes back to
        sleep, which is cheaper than a wake per disarm."""
        with self._lock:
            self._armed.pop(token, None)

    def watching(self, name: str, timeout_s: float, **info):
        """``with hw.watching("wire_op", 5.0): ...`` arm/disarm guard."""
        return _WatchContext(self, name, timeout_s, info)

    def armed_count(self) -> int:
        with self._lock:
            return len(self._armed)

    def close(self) -> None:
        """Stop the worker (tests/bench teardown; the process-default
        instance just dies with the process)."""
        with self._lock:
            self._closed = True
            self._armed.clear()
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- worker ------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
                now = self._clock()
                expired = [a for a in self._armed.values() if a.deadline <= now]
                for a in expired:
                    del self._armed[a.token]
                if not expired:
                    nxt = min(
                        (a.deadline for a in self._armed.values()), default=None
                    )
                    # bounded sleep even when idle so close() can't race a
                    # missed notify into a stuck join
                    wait_s = min(nxt - now, 60.0) if nxt is not None else 60.0
                    self._next_wake = now + wait_s
                    self._wake.wait(timeout=wait_s)
                    self._next_wake = None
                    continue
            for a in expired:
                self._fire(a)

    def _fire(self, a: _Armed) -> None:
        info = {
            "watcher": a.name, "timeout_s": round(a.timeout_s, 3),
            "armed_by_thread": a.thread,
            **{k: str(v) for k, v in a.info.items()},
        }
        log.error(
            "hangwatch: %r exceeded its %.3fs deadline (armed by thread %s; "
            "context %s) — dumping stacks + postmortem bundle",
            a.name, a.timeout_s, a.thread, a.info,
        )
        self.registry.counter(
            "hang_suspected_total", "deadline-watchdog expiries",
            labels=("watcher",),
        ).inc(watcher=a.name)
        self.recorder.record("hang_suspected", **info)
        bundle = None
        try:
            bundle = self.recorder.dump(f"hang_{a.name}", extra=info)
            log.error("hangwatch: bundle at %s", bundle)
        except Exception:  # noqa: BLE001 — the watchdog must survive
            pass
        with self._lock:
            self.fired.append({**info, "bundle": bundle})


class _WatchContext:
    def __init__(self, hw: HangWatch, name: str, timeout_s: float, info: dict):
        self._hw = hw
        self._args = (name, timeout_s, info)
        self._token: int | None = None

    def __enter__(self):
        name, timeout_s, info = self._args
        self._token = self._hw.arm(name, timeout_s, **info)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            self._hw.disarm(self._token)
        return False


_default: HangWatch | None = None
_default_lock = threading.Lock()


def get_hangwatch() -> HangWatch:
    """The process-default watchdog (bound to the default registry)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = HangWatch()
    return _default
