"""Training-health sentinels: NaN/Inf loss, grad-norm blowup, loss spikes.

Pod-scale practice loses more runs to silent numeric blowups than to
clean crashes: a NaN at step k quietly poisons every later step and the
job burns its allocation emitting garbage. These sentinels watch the
values the step ALREADY produces — the trainer checks them at its
existing ``loss_sync`` point, so the fused step gains **no extra
device→host syncs** (the loss scalar is already on the host there).

Three sentinels, each with its own policy:

===========  ==========================================================
sentinel     trips when
===========  ==========================================================
nonfinite    the synced loss (or a provided grad norm) is NaN/±Inf
spike        the loss's z-score over a trailing window exceeds
             ``spike_z`` (after ``spike_min_steps`` warmup samples)
gradnorm     a provided global grad norm exceeds ``gradnorm_max``
===========  ==========================================================

Policies: ``off`` | ``warn`` (log + count) | ``dump`` (also write a
flight-recorder postmortem bundle, once per sentinel) | ``halt`` (dump,
then raise :class:`SentinelTripped` so the run stops AT the failure with
the bundle on disk instead of hours later with a truncated log).

Configured via ``DSML_SENTINELS``: unset/``0`` disables; ``1`` enables
the defaults (``nonfinite=halt,spike=warn,gradnorm=warn``); a bare
policy name applies to every sentinel; ``name=policy,...`` sets them
individually. Every trip increments
``sentinel_trips_total{sentinel,policy}``.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import threading

from dsml_tpu.obs import flight_recorder
from dsml_tpu.obs.registry import Registry, get_registry
from dsml_tpu.utils.logging import get_logger

__all__ = [
    "SentinelTripped",
    "SentinelConfig",
    "TrainingSentinels",
    "SENTINELS",
    "POLICIES",
]

log = get_logger("sentinels")

SENTINELS = ("nonfinite", "spike", "gradnorm")
POLICIES = ("off", "warn", "dump", "halt")


class SentinelTripped(RuntimeError):
    """A ``halt``-policy sentinel fired. Carries the bundle path so the
    catcher (or the operator reading the traceback) finds the postmortem."""

    def __init__(self, sentinel: str, message: str, bundle: str | None = None):
        super().__init__(message)
        self.sentinel = sentinel
        self.bundle = bundle


@dataclasses.dataclass
class SentinelConfig:
    nonfinite: str = "halt"
    spike: str = "warn"
    gradnorm: str = "warn"
    spike_z: float = 6.0        # z-score threshold over the trailing window
    spike_window: int = 64      # trailing losses kept
    spike_min_steps: int = 16   # warmup before the z-score is trusted
    gradnorm_max: float = 1e4   # absolute global-grad-norm ceiling

    def __post_init__(self):
        for name in SENTINELS:
            policy = getattr(self, name)
            if policy not in POLICIES:
                raise ValueError(
                    f"sentinel {name}: unknown policy {policy!r} "
                    f"(choose from {POLICIES})"
                )

    @classmethod
    def from_env(cls, spec: str | None = None) -> "SentinelConfig | None":
        """Parse ``DSML_SENTINELS`` (or an explicit ``spec``). Returns
        ``None`` when sentinels are disabled."""
        if spec is None:
            spec = os.environ.get("DSML_SENTINELS", "")
        spec = spec.strip()
        if spec.lower() in ("", "0", "false", "off"):
            return None
        if spec.lower() in ("1", "true", "on"):
            return cls()
        if spec in POLICIES:
            return cls(nonfinite=spec, spike=spec, gradnorm=spec)
        kv = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"DSML_SENTINELS entry {part!r} is neither a policy "
                    f"({POLICIES}) nor name=policy"
                )
            name, _, policy = part.partition("=")
            name, policy = name.strip(), policy.strip()
            if name in ("spike_z", "gradnorm_max"):
                kv[name] = float(policy)
            elif name in ("spike_window", "spike_min_steps"):
                kv[name] = int(policy)
            elif name in SENTINELS:
                kv[name] = policy
            else:
                raise ValueError(
                    f"DSML_SENTINELS names unknown sentinel {name!r} "
                    f"(choose from {SENTINELS})"
                )
        return cls(**kv)


class TrainingSentinels:
    """Stateful checker; one instance per training run (thread-safe).

    ``check(step, loss, grad_norm=None)`` is the whole API: call it with
    host floats at a point where they are already synced. Policies
    ``dump``/``halt`` write a flight-recorder bundle (at most one dump per
    sentinel per run — a NaN that poisons every later loss must not fill
    the disk with identical bundles).
    """

    def __init__(self, config: SentinelConfig | None = None,
                 registry: Registry | None = None,
                 recorder: "flight_recorder.FlightRecorder | None" = None):
        self.config = config if config is not None else SentinelConfig()
        self.registry = registry if registry is not None else get_registry()
        self.recorder = (recorder if recorder is not None
                         else flight_recorder.get_flight_recorder())
        self._lock = threading.Lock()
        # trailing window with RUNNING sum/sum-of-squares: the z-score is
        # O(1) per check, not O(window) — this sits on the per-step path
        self._window: collections.deque = collections.deque(
            maxlen=max(self.config.spike_window, 2)
        )
        self._win_sum = 0.0
        self._win_sumsq = 0.0
        self._dumped: set[str] = set()
        self.trips: list[dict] = []

    @classmethod
    def maybe_from_env(cls, registry: Registry | None = None,
                       recorder=None) -> "TrainingSentinels | None":
        """The trainer's hook: an instance when ``DSML_SENTINELS`` asks for
        one, else ``None`` (zero per-step cost)."""
        cfg = SentinelConfig.from_env()
        if cfg is None:
            return None
        return cls(cfg, registry=registry, recorder=recorder)

    # -- the check ---------------------------------------------------------

    def check(self, step: int, loss: float, grad_norm: float | None = None) -> None:
        """Inspect one step's host-side values; raises
        :class:`SentinelTripped` under a ``halt`` policy."""
        cfg = self.config
        loss = float(loss)
        if not math.isfinite(loss):
            self._trip("nonfinite", step,
                       f"loss is {loss!r} at step {step}", loss=loss)
        else:
            with self._lock:
                z = self._zscore_locked(loss)
                if len(self._window) == self._window.maxlen:
                    old = self._window[0]  # about to be evicted by append
                    self._win_sum -= old
                    self._win_sumsq -= old * old
                self._window.append(loss)
                self._win_sum += loss
                self._win_sumsq += loss * loss
            if z > cfg.spike_z:
                self._trip(
                    "spike", step,
                    f"loss {loss:.6g} is {z:.1f} sigma above the trailing "
                    f"mean at step {step}", loss=loss, z=round(z, 2),
                )
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if not math.isfinite(grad_norm):
                self._trip("nonfinite", step,
                           f"global grad norm is {grad_norm!r} at step {step}",
                           grad_norm=grad_norm)
            elif grad_norm > cfg.gradnorm_max:
                self._trip(
                    "gradnorm", step,
                    f"global grad norm {grad_norm:.6g} exceeds "
                    f"{cfg.gradnorm_max:.6g} at step {step}",
                    grad_norm=grad_norm,
                )

    def _zscore_locked(self, loss: float) -> float:
        """z-score of ``loss`` against the trailing window (0 before the
        warmup fills). Caller holds ``self._lock``."""
        n = len(self._window)
        if n < max(self.config.spike_min_steps, 2):
            return 0.0
        mean = self._win_sum / n
        var = max(self._win_sumsq / n - mean * mean, 0.0)
        return (loss - mean) / max(math.sqrt(var), 1e-12)

    def spike_zscore(self, loss: float) -> float:
        """The z-score ``check`` would compute for ``loss`` right now —
        including the warmup guard (0.0 until ``spike_min_steps`` samples).
        Read-only; exposed for tests pinning the math."""
        with self._lock:
            return self._zscore_locked(float(loss))

    # -- policy execution --------------------------------------------------

    def _trip(self, sentinel: str, step: int, message: str, **info) -> None:
        policy = getattr(self.config, sentinel)
        if policy == "off":
            return
        rec = {"sentinel": sentinel, "policy": policy, "step": step,
               "message": message, **info}
        with self._lock:
            self.trips.append(rec)
        self.registry.counter(
            "sentinel_trips_total", "training-health sentinel trips",
            labels=("sentinel", "policy"),
        ).inc(sentinel=sentinel, policy=policy)
        self.recorder.record("sentinel_trip", **rec)
        log.warning("sentinel %s [%s]: %s", sentinel, policy, message)
        bundle = None
        if policy in ("dump", "halt"):
            with self._lock:
                first = sentinel not in self._dumped
                self._dumped.add(sentinel)
            if first:
                bundle = self.recorder.dump(f"sentinel_{sentinel}", extra=rec)
                log.warning("sentinel %s: postmortem bundle at %s",
                            sentinel, bundle)
        if policy == "halt":
            raise SentinelTripped(sentinel, message, bundle=bundle)
