"""Per-step breakdown + goodput/MFU accounting.

Three accounting layers a production training service needs and the
reference never had:

- :class:`StepBreakdown` — where a step's wall time goes: ``data`` /
  ``forward_backward`` / ``grad_sync`` / ``optimizer`` /
  ``checkpoint_stall`` (the canonical phases; arbitrary names accepted).
  In a FUSED jitted step the middle three are one program — the trainer
  records ``step_dispatch`` + ``loss_sync`` instead, and the phased
  decomposition lives in ``bench.py --section obs``, where each phase is
  its own fenced program and the components must sum to within 5% of the
  measured wall (the acceptance bar).
- :class:`GoodputTracker` — productive step time ÷ wall time across
  preemption/restore events (the Google "goodput" metric): every second
  spent re-doing work after a restore, blocked on a checkpoint, or idle
  between epochs shows up as the gap between the two.
- :func:`mfu` — achieved model FLOP/s ÷ the chip's peak, with the FLOP
  numerators computed analytically by ``models.common``
  (``transformer_train_flops`` / ``mlp_train_flops`` — the same
  accounting ``bench.py`` reports).

Everything here is clock arithmetic — no jax imports, safe in any
process. ``clock=`` is injectable for deterministic tests.
"""

from __future__ import annotations

import contextlib
import threading
import time

from dsml_tpu.obs.registry import Registry, get_registry

__all__ = ["StepBreakdown", "GoodputTracker", "mfu", "STEP_PHASES"]

# the canonical phase taxonomy (docs/OBSERVABILITY.md); add() accepts any
# name — these are the ones the trainer/bench emit
STEP_PHASES = (
    "data", "forward_backward", "grad_sync", "optimizer", "checkpoint_stall",
)


class StepBreakdown:
    """Accumulates per-phase seconds and per-step walls; thread-safe."""

    def __init__(self, registry: Registry | None = None,
                 clock=time.perf_counter):
        self.registry = registry if registry is not None else get_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._phase_s: dict[str, float] = {}
        self._phase_n: dict[str, int] = {}
        self._step_wall_s = 0.0
        self._steps = 0
        self._hist = self.registry.histogram(
            "step_phase_ms", "per-step phase durations", labels=("phase",)
        )

    def add(self, phase: str, seconds: float) -> None:
        """Record ``seconds`` spent in ``phase`` (explicit form — the hot
        loop reads the clock itself and pays no context-manager frames)."""
        with self._lock:
            self._phase_s[phase] = self._phase_s.get(phase, 0.0) + seconds
            self._phase_n[phase] = self._phase_n.get(phase, 0) + 1
        self._hist.observe(seconds * 1e3, phase=phase)

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        t0 = self._clock()
        try:
            yield self
        finally:
            if fence is not None:
                import jax

                jax.block_until_ready(fence)
            self.add(name, self._clock() - t0)

    @contextlib.contextmanager
    def step(self):
        """Wrap one whole step; its wall time is the coverage denominator."""
        t0 = self._clock()
        try:
            yield self
        finally:
            with self._lock:
                self._step_wall_s += self._clock() - t0
                self._steps += 1

    def note_step_wall(self, seconds: float) -> None:
        with self._lock:
            self._step_wall_s += seconds
            self._steps += 1

    def summary(self) -> dict:
        """Per-phase totals/means plus ``coverage_pct`` — how much of the
        measured step wall the recorded phases account for (100% means the
        breakdown explains the whole step; the bench obs section requires
        >= 95%)."""
        with self._lock:
            phases = {
                name: {
                    "total_s": round(total, 6),
                    "mean_ms": round(total / max(self._phase_n[name], 1) * 1e3, 3),
                    "count": self._phase_n[name],
                }
                for name, total in self._phase_s.items()
            }
            wall, steps = self._step_wall_s, self._steps
        phase_sum = sum(p["total_s"] for p in phases.values())
        out = {
            "phases": phases,
            "phase_sum_s": round(phase_sum, 6),
            "steps": steps,
            "step_wall_s": round(wall, 6),
        }
        if wall > 0:
            out["step_wall_mean_ms"] = round(wall / max(steps, 1) * 1e3, 3)
            out["coverage_pct"] = round(100.0 * phase_sum / wall, 2)
        return out


class GoodputTracker:
    """Productive-time ÷ wall-time accounting across preemptions/restores.

    ``wall`` runs from construction (or the injected clock's first read);
    ``productive`` accumulates only inside :meth:`productive` blocks (or
    explicit :meth:`add_productive` seconds). Preemption/restore/save
    events are timestamped marks, so the exported record shows WHERE the
    non-productive time went. A preempted-and-restarted run carries its
    prior productive seconds forward via ``carry_s`` — goodput then spans
    the whole job, not just the current incarnation.
    """

    def __init__(self, registry: Registry | None = None,
                 clock=time.monotonic, carry_s: float = 0.0):
        self.registry = registry if registry is not None else get_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._productive_s = float(carry_s)
        self.events: list[dict] = []

    @contextlib.contextmanager
    def productive(self):
        t0 = self._clock()
        try:
            yield self
        finally:
            self.add_productive(self._clock() - t0)

    def add_productive(self, seconds: float) -> None:
        with self._lock:
            self._productive_s += seconds

    def mark(self, event: str, **info) -> None:
        """Timestamp a lifecycle event (``preemption`` / ``restore`` /
        ``checkpoint_save`` / ``checkpoint_gc`` ...)."""
        rec = {"event": event, "t_s": round(self._clock() - self._t0, 6), **info}
        with self._lock:
            self.events.append(rec)
        self.registry.counter(
            "goodput_events_total", "goodput lifecycle events", labels=("event",)
        ).inc(event=event)

    @property
    def wall_s(self) -> float:
        return self._clock() - self._t0

    @property
    def productive_s(self) -> float:
        with self._lock:
            return self._productive_s

    def goodput(self) -> float:
        """productive / wall in [0, 1] (0 when no wall has elapsed)."""
        wall = self.wall_s
        if wall <= 0:
            return 0.0
        return min(self.productive_s / wall, 1.0)

    def summary(self) -> dict:
        g = self.goodput()
        self.registry.gauge("goodput_ratio", "productive/wall").set(g)
        with self._lock:
            events = list(self.events)
        return {
            "wall_s": round(self.wall_s, 6),
            "productive_s": round(self.productive_s, 6),
            "goodput": round(g, 4),
            "events": events,
        }


def mfu(achieved_flops_per_s: float, peak_flops_per_s: float | None) -> float | None:
    """Model FLOPs utilization: achieved ÷ peak (None when the chip's peak
    is unknown — never guess a denominator)."""
    if not peak_flops_per_s or peak_flops_per_s <= 0:
        return None
    return achieved_flops_per_s / peak_flops_per_s
