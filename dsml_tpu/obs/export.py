"""Export surfaces: rotation-safe JSONL sink + in-process HTTP /metrics.

- :class:`MetricsLogger` — the append-only JSON-lines history that grew
  out of ``utils.metrics`` (still re-exported there for compat), now
  rotation-safe: ``max_bytes`` caps the file, rotating ``path`` →
  ``path.1`` atomically so a long-running trainer cannot fill a disk.
- :func:`start_metrics_server` — OPT-IN in-process HTTP endpoint serving
  the registry's Prometheus text at ``/metrics``, the JSON snapshot at
  ``/metrics.json``, and the identity-stamped CLUSTER snapshot (metrics +
  Chrome trace + clock reading) at ``/cluster.json`` — the scrape surface
  ``obs.cluster.ClusterAggregator`` merges fleet-wide (scrape-able by
  Prometheus or curl; nothing listens unless a caller asks).

Histogram records in the JSON expositions (``/metrics.json``, JSONL,
``/cluster.json``) carry per-bucket trace_id EXEMPLARS when the emitting
call site attached them (``Histogram.observe(..., exemplar=trace_id)`` —
the serving TTFT/TPOT/admission histograms do), so a scraped tail bucket
resolves to a concrete request trace (docs/OBSERVABILITY.md § Request
tracing & SLO budgets). Prometheus 0.0.4 text has no exemplar syntax;
they ride the JSON forms only.
"""

from __future__ import annotations

import json
import os
import threading
import time

from dsml_tpu.obs.registry import ObsUnavailable, Registry, get_registry

__all__ = ["MetricsLogger", "MetricsServer", "start_metrics_server"]


class MetricsLogger:
    """Append-only JSON-lines metrics history with wall-clock timestamps.

    ``path=None`` keeps records in memory only. With a path, every record
    appends a line; when ``max_bytes`` is set and the file would exceed it,
    the file rotates to ``<path>.1`` first (one generation — enough to
    bound disk while keeping the recent history greppable)."""

    def __init__(self, path: str | None = None, max_bytes: int | None = None):
        self.path = path
        self.max_bytes = max_bytes
        self.records: list[dict] = []
        self._lock = threading.Lock()

    def log(self, **kv) -> dict:
        rec = {"time": time.time(), **kv}
        line = json.dumps(rec) + "\n"
        with self._lock:
            self.records.append(rec)
            if self.path:
                self._maybe_rotate(len(line))
                with open(self.path, "a") as f:
                    f.write(line)
        return rec

    def _maybe_rotate(self, incoming: int) -> None:
        if not self.max_bytes:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming > self.max_bytes:
            # os.replace is atomic on one filesystem: a concurrent reader
            # sees either the old full file or the fresh one, never a
            # truncated hybrid
            os.replace(self.path, self.path + ".1")

    def last(self, **match) -> dict | None:
        with self._lock:
            records = list(self.records)
        for rec in reversed(records):
            if all(rec.get(k) == v for k, v in match.items()):
                return rec
        return None


class MetricsServer:
    """Handle for a running /metrics endpoint (see
    :func:`start_metrics_server`)."""

    def __init__(self, httpd, thread):
        self._httpd = httpd
        self._thread = thread
        self.port = httpd.server_address[1]
        self.address = f"http://{httpd.server_address[0]}:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_metrics_server(registry: Registry | None = None, port: int = 0,
                         host: str = "127.0.0.1",
                         role: str | None = None,
                         tracer=None) -> MetricsServer:
    """Serve ``registry`` on a daemon thread. ``port=0`` picks a free
    port (read it back from the handle). ``role`` labels this process in
    ``/cluster.json`` snapshots (default: ``DSML_OBS_ROLE``) and
    ``tracer`` pairs them with the matching span trace — pass it whenever
    ``registry`` is a private instance, or the snapshot would couple
    private metrics with the GLOBAL tracer's unrelated spans. Raises
    :class:`ObsUnavailable` when the port cannot be bound, with the
    conflicting address named."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else get_registry()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path.split("?")[0] == "/metrics":
                body = reg.to_prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/metrics.json":
                body = json.dumps(reg.collect()).encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/cluster.json":
                from dsml_tpu.obs.cluster import snapshot

                body = json.dumps(
                    snapshot(role=role, registry=reg, tracer=tracer)
                ).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    try:
        httpd = ThreadingHTTPServer((host, port), Handler)
    except OSError as e:
        raise ObsUnavailable(
            f"cannot bind metrics endpoint on {host}:{port}: {e}; pick a "
            "free port (port=0 auto-selects) or skip the HTTP exporter — "
            "Registry.to_prometheus_text()/dump_jsonl() need no socket"
        ) from e
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="obs-metrics-http")
    thread.start()
    return MetricsServer(httpd, thread)
