"""HBM memory ledger: per-subsystem device-memory attribution.

Every HBM number the framework reported before this module was ANALYTIC —
the long-context headroom table, the paged-KV capacity ratios, and
``plan_mesh``'s first-order arithmetic all derive bytes instead of
measuring them, and ``parallel/auto.py`` fell back to a 16 GB constant
when ``memory_stats()`` was absent. The obs plane measured time (spans),
failures (forensics), the fleet (cluster merge), and requests (tracing) —
memory was the one dimension with no instrument. This module is that
instrument: a :class:`MemoryLedger` that attributes device bytes to the
subsystem that allocated them and reconciles the claims against the
backend's own ``jax.Device.memory_stats()`` at scrape time.

Attribution model — two kinds of claim:

- **static claims** (:meth:`MemoryLedger.set_claim` /
  :meth:`claim_tree`): a subsystem states its resident bytes once, at
  the allocation site (trainer params/optimizer state, error-feedback
  residuals, a measured activation footprint). ``claim_tree`` counts a
  pytree's per-device resident bytes through each array's addressable
  shards, so an fsdp-sharded optimizer claims its SHARD, not the
  logical tree.
- **live sources** (:meth:`register_source`): a callable re-read at
  every scrape — the paged KV pool's live/shared/free split, the
  migration donor's in-flight staging spans, the checkpoint writer's
  queued host snapshots. Sources are held by weak reference: a retired
  batcher's pool drops out of the ledger with the batcher, no
  unregister calls to forget.

Reconciliation (``docs/OBSERVABILITY.md`` § Memory ledger): a registry
collect hook refreshes the gauges at every exposition —
``hbm_claimed_bytes{subsystem,detail}``, ``hbm_measured_bytes{kind}``
(bytes_in_use / peak_bytes_in_use / bytes_limit, when the backend
reports them), ``hbm_headroom_bytes``, and the drift-visibility residual
``hbm_unattributed_bytes = measured − claimed``. Provenance is always
explicit (``hbm_source{source}``): "memory_stats" when a device reported,
"claimed" when the ledger's own attribution is the only number —
the consumer can always tell a measurement from bookkeeping.

Zero-overhead-by-default contract (same as the registry's): every write
early-returns on one enabled check; :meth:`note_step_peak` — the per-step
watermark the trainer/hybrid step record — additionally caches
"this backend reports no stats" after the first full miss, so a CPU run
never re-polls eight devices per step.

OOM forensics: :func:`is_oom` recognizes RESOURCE_EXHAUSTED /
out-of-memory shapes, :func:`maybe_dump_oom` writes a postmortem bundle
whose ``memory.json`` carries the ledger snapshot, the watermark
timeline, and every live source's last reading (the page-pool state) —
the flight recorder's crash hooks route OOM-shaped unhandled exceptions
through the same path.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import weakref

from dsml_tpu.obs.registry import Registry, get_registry

__all__ = [
    "SUBSYSTEMS",
    "MemoryLedger",
    "get_memory_ledger",
    "tree_nbytes",
    "is_oom",
    "maybe_dump_oom",
]

SCHEMA = "dsml.obs.memory_ledger/1"

# the attribution taxonomy (docs/OBSERVABILITY.md § Memory ledger); new
# subsystems are allowed — this tuple documents the canonical set the
# wired hot paths use, it is not an enum the ledger enforces
SUBSYSTEMS = (
    "params",              # model weights as placed on the mesh
    "optimizer",           # optimizer state (adam m/v, ZeRO-2 shards)
    "error_feedback",      # quantized-sync EF residuals (per-rank shards)
    "kv_pages",            # paged KV pool (live/shared/free/scratch split)
    "weights_quant",       # block-quantized serving weights (packed/scales)
    "migration_staging",   # P2P shard-motion staging spans in flight
    "checkpoint_staging",  # async-writer host snapshots awaiting commit
    "activations",         # XLA step temps (measured_activation_bytes)
)

# subsystems whose claims are HOST bytes (a queued checkpoint snapshot
# lives in RAM): reported like every claim, but EXCLUDED from the
# device-reconciliation residual — host bytes inflating the claimed
# total would drive hbm_unattributed_bytes negative by a full snapshot
# during every async commit and fire false drift alarms
HOST_SUBSYSTEMS = frozenset({"checkpoint_staging"})

# bounded per-process watermark timeline: enough to cover thousands of
# sync windows without growing host memory; a postmortem carries the tail
WATERMARK_CAP = 512

# textual shapes of a device OOM across the runtimes we sit on: XLA's
# RESOURCE_EXHAUSTED status, PJRT "Out of memory" allocator messages, the
# comm layer's grpc RESOURCE_EXHAUSTED staging rejections
_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory",
                "hbm_oom", "allocation failure")


def tree_nbytes(tree, per_device: bool = False) -> int:
    """Resident bytes of ``tree``'s array leaves.

    ``per_device=False`` — the logical total (sum of ``leaf.nbytes``).
    ``per_device=True`` — the HBM-binding number: device-sharded arrays
    count each addressable shard's bytes against its device and the MAX
    over devices is returned (a replicated leaf costs its full bytes per
    device; an 8-way shard costs an eighth), plus host-side leaves (numpy
    arrays) counted once. Non-array leaves (scalars, None) are free.
    """
    import jax

    host_total = 0
    per_dev: dict = {}
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            continue
        if per_device and isinstance(leaf, jax.Array):
            try:
                shards = leaf.addressable_shards
            except Exception:  # noqa: BLE001 — deleted/donated buffers
                shards = None
            if shards:
                for s in shards:
                    per_dev[s.device] = per_dev.get(s.device, 0) + int(s.data.nbytes)
                continue
        host_total += int(nbytes)
    if per_device and per_dev:
        return max(per_dev.values()) + host_total
    return host_total


def _device_memory_stats() -> list[dict] | None:
    """Per-device ``memory_stats()`` rows, ONLY when jax is already
    imported — a scrape (or a postmortem dump) must never initialize a
    backend. Devices that report nothing are omitted. The return value
    distinguishes two kinds of "no rows": ``[]`` = the backend was polled
    CLEANLY and reports no stats (cacheable — a statless CPU mesh stays
    statless), ``None`` = the poll itself failed (jax absent, device
    enumeration raised, every device call errored — the half-dead-backend
    window during an elastic recovery) and MUST be retried, never cached
    as "this backend has no memory instrument"."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend may be half-dead
        return None
    out = []
    polled_clean = not devices  # zero devices = a clean (odd) answer
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001
            continue
        polled_clean = True  # at least one device ANSWERED (maybe None)
        if not stats:
            continue
        out.append({
            "device": str(d),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get(
                "peak_bytes_in_use", stats.get("bytes_in_use", 0))),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out if (out or polled_clean) else None


class MemoryLedger:
    """Per-subsystem device-byte attribution bound to one registry.

    All writes no-op (one enabled check) when the registry is disabled;
    reads (:meth:`claimed`, :meth:`measure`, :meth:`snapshot`) always
    work — a postmortem of a disabled-registry process still carries
    whatever the live sources can tell it.
    """

    def __init__(self, registry: Registry | None = None, stats_fn=None):
        self.registry = registry if registry is not None else get_registry()
        # injectable for tests/bench: () -> list of per-device stat rows
        self._stats_fn = stats_fn if stats_fn is not None else _device_memory_stats
        self._lock = threading.Lock()
        self._claims: dict[tuple[str, str], float] = {}  # (subsystem, detail)
        # (subsystem, name, weakref-to-callable); pruned on read
        self._sources: list[tuple[str, str, object]] = []
        self._watermarks: collections.deque = collections.deque(maxlen=WATERMARK_CAP)
        # None = unknown yet; False = first full poll found no stats
        # (cached so note_step_peak never re-polls a statless backend)
        self._stats_available: bool | None = None
        # (nbytes, batch) of the last measured activation footprint —
        # kept WITH its geometry so consumers rescale instead of reusing
        # a number measured at a different per-device batch verbatim
        self._act_measurement: tuple[float, int] | None = None
        # gauges refresh at scrape time, not write time — derived values
        # (unattributed, headroom) depend on the live measure
        self.registry.add_collect_hook(self._refresh_gauges)

    # -- claims ------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.registry.enabled

    def set_claim(self, subsystem: str, nbytes: float,
                  detail: str = "total") -> None:
        """State ``subsystem``'s resident device bytes (absolute, not a
        delta — re-claiming replaces). No-op when disabled."""
        if not self.registry.enabled:
            return
        with self._lock:
            self._claims[(str(subsystem), str(detail))] = float(max(nbytes, 0.0))

    def clear_claim(self, subsystem: str, detail: str | None = None) -> None:
        """Drop a subsystem's claim (``detail=None`` = every detail)."""
        with self._lock:
            self._claims = {
                k: v for k, v in self._claims.items()
                if not (k[0] == subsystem and (detail is None or k[1] == detail))
            }

    def claim_tree(self, subsystem: str, tree, detail: str = "total") -> int:
        """Claim a pytree's per-device resident bytes (see
        :func:`tree_nbytes`); returns the bytes claimed (0 when disabled —
        the tree is never walked)."""
        if not self.registry.enabled:
            return 0
        nbytes = tree_nbytes(tree, per_device=True)
        self.set_claim(subsystem, nbytes, detail=detail)
        return nbytes

    def record_activation_measurement(self, nbytes: float,
                                      batch: int) -> None:
        """Record a MEASURED activation/workspace footprint together with
        the batch it was measured at (the trainer's ``DSML_MEASURE_ACT``
        wiring). Claims the resident bytes for reconciliation AND keeps
        the per-sample figure so :func:`plan_mesh` can rescale to ITS
        ``batch_per_device`` — an elastic shrink re-plan (same global
        batch, fewer chips, larger per-device batch) must not consume the
        stale absolute number."""
        if not self.registry.enabled:
            return
        self.set_claim("activations", nbytes, detail="measured_step_temp")
        with self._lock:
            self._act_measurement = (float(nbytes), max(int(batch), 1))

    def activation_bytes_for(self, batch_per_device: int) -> float | None:
        """The measured activation footprint rescaled linearly (the
        first-order batch dependence) to ``batch_per_device``; None when
        nothing was measured."""
        with self._lock:
            m = self._act_measurement
        if m is None:
            return None
        nbytes, batch = m
        return nbytes / batch * max(int(batch_per_device), 1)

    def register_source(self, subsystem: str, fn, name: str = "0") -> None:
        """Register a live byte source re-read at every scrape/snapshot.
        ``fn() -> bytes | {detail: bytes}``. Weakly held: the source dies
        with its owner. Registration is unconditional (cheap) so a ledger
        enabled mid-run sees sources wired while it was off."""
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        with self._lock:
            # same (subsystem, name) re-registers (an owner rebuilt)
            self._sources = [
                s for s in self._sources
                if not (s[0] == subsystem and s[1] == name)
            ]
            self._sources.append((str(subsystem), str(name), ref))

    def _read_sources(self) -> dict[tuple[str, str], float]:
        """Pull every live source; prune the dead. A broken source must
        not break a scrape (or the postmortem that wants the others)."""
        with self._lock:
            sources = list(self._sources)
        out: dict[tuple[str, str], float] = {}
        dead = []
        for subsystem, name, ref in sources:
            fn = ref()
            if fn is None:
                dead.append((subsystem, name, ref))
                continue
            try:
                got = fn()
            except Exception:  # noqa: BLE001
                continue
            if isinstance(got, dict):
                for detail, nbytes in got.items():
                    key = (subsystem, str(detail))
                    out[key] = out.get(key, 0.0) + float(nbytes)
            elif got is not None:
                key = (subsystem, name)
                out[key] = out.get(key, 0.0) + float(got)
        if dead:
            with self._lock:
                self._sources = [s for s in self._sources if s not in dead]
        return out

    def claimed(self) -> dict[str, dict[str, float]]:
        """{subsystem: {detail: bytes}} — static claims merged with a
        fresh read of every live source (sources sum into their detail)."""
        with self._lock:
            merged = dict(self._claims)
        for key, nbytes in self._read_sources().items():
            merged[key] = merged.get(key, 0.0) + nbytes
        out: dict[str, dict[str, float]] = {}
        for (subsystem, detail), nbytes in sorted(merged.items()):
            out.setdefault(subsystem, {})[detail] = nbytes
        return out

    def static_claimed_bytes(self) -> float:
        """Sum of the STATIC claims only — one lock + dict sum, no source
        callables, no cross-subsystem locks. The per-step watermark's
        fallback value on statless backends: a train step must never walk
        the serving pools' or the donor's lock-guarded state."""
        with self._lock:
            return float(sum(self._claims.values()))

    def claimed_bytes(self, subsystem: str | None = None,
                      details: tuple | None = None) -> float:
        """Total claimed bytes — one subsystem's (optionally restricted to
        ``details``) or the whole ledger's. Reads every live source; for
        hot paths use :meth:`static_claimed_bytes`."""
        claims = self.claimed()
        if subsystem is not None:
            claims = {subsystem: claims.get(subsystem, {})}
        return float(sum(
            nbytes
            for per_detail in claims.values()
            for detail, nbytes in per_detail.items()
            if details is None or detail in details
        ))

    # -- measurement -------------------------------------------------------

    def measure(self) -> dict:
        """The backend's own numbers, aggregated per-chip-conservatively:
        ``bytes_in_use``/``peak_bytes_in_use`` are the MAX over devices
        (the binding chip), ``bytes_limit``/``headroom`` the MIN. Returns
        ``{"available": False, "source": "claimed"}`` when no device
        reports stats — callers must branch on provenance, never on a
        guessed constant."""
        rows = self._stats_fn() if self._stats_available is not False else []
        if (self._stats_available is None and rows is not None
                and self._stats_fn is _device_memory_stats):
            # cache only a CLEAN poll outcome (rows=None = the poll itself
            # failed — a transient half-dead backend must not demote every
            # later watermark/reconciliation to "claimed" for the process
            # lifetime; retry on the next measure)
            self._stats_available = bool(rows)
        if not rows:
            return {"available": False, "source": "claimed", "devices": 0}
        in_use = max(r["bytes_in_use"] for r in rows)
        peak = max(r["peak_bytes_in_use"] for r in rows)
        limits = [r["bytes_limit"] for r in rows if r["bytes_limit"]]
        limit = min(limits) if limits else 0
        return {
            "available": True,
            "source": "memory_stats",
            "devices": len(rows),
            "bytes_in_use": in_use,
            "peak_bytes_in_use": peak,
            "bytes_limit": limit,
            "headroom_bytes": (limit - in_use) if limit else None,
            "per_device": rows,
        }

    def headroom_bytes(self) -> float | None:
        """Measured per-chip headroom (min over devices), or None when the
        backend reports no stats — the paged batcher's pressure reading
        and the elastic planner both branch on None rather than inventing
        a constant."""
        m = self.measure()
        return m.get("headroom_bytes") if m["available"] else None

    def device_claimed_bytes(self) -> float:
        """Claimed DEVICE bytes: the full claimed total minus
        :data:`HOST_SUBSYSTEMS` — the side reconciliation compares
        against ``memory_stats`` (host-RAM claims like a queued
        checkpoint snapshot must not enter a device residual)."""
        claims = self.claimed()
        return float(sum(
            nbytes
            for subsystem, per_detail in claims.items()
            if subsystem not in HOST_SUBSYSTEMS
            for nbytes in per_detail.values()
        ))

    def unattributed_bytes(self) -> float | None:
        """``measured bytes_in_use − claimed DEVICE total`` — the drift
        gauge (host-subsystem claims excluded; see
        :data:`HOST_SUBSYSTEMS`). None when nothing is measured (there is
        no residual against pure bookkeeping)."""
        m = self.measure()
        if not m["available"]:
            return None
        return float(m["bytes_in_use"]) - self.device_claimed_bytes()

    # -- watermarks --------------------------------------------------------

    def note_step_peak(self, step: int | None = None,
                       label: str | None = None) -> None:
        """Record one watermark: the measured peak when the backend
        reports one, else the STATIC claimed total (source-stamped either
        way — live sources are deliberately excluded here: walking the
        serving pools' and the donor's lock-guarded state per train step
        would turn a watermark into cross-subsystem lock traffic; the
        scrape-time gauges and snapshots carry the full source-inclusive
        picture). The trainer calls this at loss syncs, the hybrid step
        after every step; one enabled check when off, one
        cached-availability check + dict sum when the backend is
        statless."""
        if not self.registry.enabled:
            return
        m = self.measure()
        if m["available"]:
            value, source = float(m["peak_bytes_in_use"]), "memory_stats"
        else:
            value, source = self.static_claimed_bytes(), "claimed"
        entry = {"t": round(time.time(), 6), "peak_bytes": value,
                 "source": source}
        if step is not None:
            entry["step"] = int(step)
        if label is not None:
            entry["label"] = str(label)
        with self._lock:
            self._watermarks.append(entry)
        self.registry.gauge(
            "hbm_step_peak_bytes",
            "last recorded per-step peak device bytes (watermark)",
            labels=("source",),
        ).set(value, source=source)

    def watermarks(self) -> list[dict]:
        with self._lock:
            return list(self._watermarks)

    def clear(self) -> None:
        """Drop claims + watermarks + the activation measurement (tests;
        a fresh bench section). Sources survive — their owners are still
        alive."""
        with self._lock:
            self._claims.clear()
            self._watermarks.clear()
            self._act_measurement = None

    # -- exposition --------------------------------------------------------

    def _refresh_gauges(self) -> None:
        """Registry collect hook: re-derive every gauge at scrape time so
        an exposition always reflects the live sources and the live
        measure, not the last write."""
        if not self.registry.enabled:
            return
        claims = self.claimed()
        claimed_gauge = self.registry.gauge(
            "hbm_claimed_bytes",
            "device bytes attributed to a subsystem by the memory ledger",
            labels=("subsystem", "detail"),
        )
        # label sets change between scrapes (a retired batcher's pool
        # drops out; provenance can flip): clear before re-deriving, or a
        # dead series would freeze at its last bytes in every exposition
        claimed_gauge.clear()
        total = device_total = 0.0
        for subsystem, per_detail in claims.items():
            for detail, nbytes in per_detail.items():
                claimed_gauge.set(nbytes, subsystem=subsystem, detail=detail)
                total += nbytes
                if subsystem not in HOST_SUBSYSTEMS:
                    device_total += nbytes
        self.registry.gauge(
            "hbm_claimed_total_bytes", "sum of every ledger claim",
        ).set(total)
        m = self.measure()
        source_gauge = self.registry.gauge(
            "hbm_source",
            "1 for the provenance the ledger's numbers carry "
            "(memory_stats = measured, claimed = bookkeeping only)",
            labels=("source",),
        )
        source_gauge.clear()  # exactly ONE provenance series at a time
        source_gauge.set(1.0, source=m["source"])
        measured_gauge = self.registry.gauge(
            "hbm_measured_bytes",
            "device memory_stats as scraped (max in-use/peak, min limit "
            "over local devices)",
            labels=("kind",),
        )
        if not m["available"]:
            # a provenance flip back to claimed (stats source gone) must
            # not leave the last measured rows frozen in the exposition
            measured_gauge.clear()
        else:
            for kind in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                measured_gauge.set(float(m[kind]), kind=kind)
            if m.get("headroom_bytes") is not None:
                self.registry.gauge(
                    "hbm_headroom_bytes",
                    "min over devices of bytes_limit - bytes_in_use",
                ).set(float(m["headroom_bytes"]))
            self.registry.gauge(
                "hbm_unattributed_bytes",
                "measured bytes_in_use minus ledger-claimed DEVICE total "
                "(host-subsystem claims excluded; attribution drift — "
                "persistent growth = an unclaimed subsystem)",
            ).set(float(m["bytes_in_use"]) - device_total)

    def snapshot(self) -> dict:
        """Self-contained machine-readable state: claims (sources
        included), measurement + provenance, residual, watermark tail."""
        claims = self.claimed()
        total = sum(n for d in claims.values() for n in d.values())
        device_total = sum(
            n for s, d in claims.items() if s not in HOST_SUBSYSTEMS
            for n in d.values()
        )
        m = self.measure()
        per_device = m.pop("per_device", None)
        snap = {
            "schema": SCHEMA,
            "time": time.time(),
            "claimed": claims,
            "claimed_total_bytes": total,
            "claimed_device_bytes": device_total,
            "measured": m,
            "unattributed_bytes": (
                float(m["bytes_in_use"]) - device_total
                if m["available"] else None
            ),
            "watermarks": self.watermarks(),
        }
        if per_device:
            snap["measured"]["per_device"] = per_device
        return snap


# one ledger per registry, stored ON the registry (shares its lifetime —
# a weak-keyed map whose value strongly referenced the key would leak
# every private bench/test registry): the default registry gets the
# default ledger; private registries get their own on first ask — the
# flight recorder resolves THROUGH its registry, so a private-recorder
# bundle never leaks the process ledger's claims
_ledgers_lock = threading.Lock()


def get_memory_ledger(registry: Registry | None = None) -> MemoryLedger:
    reg = registry if registry is not None else get_registry()
    with _ledgers_lock:
        ledger = getattr(reg, "_memory_ledger", None)
        if ledger is None:
            ledger = reg._memory_ledger = MemoryLedger(registry=reg)
        return ledger


def is_oom(exc: BaseException | None) -> bool:
    """Is this exception device-memory-exhaustion shaped? Matches XLA's
    RESOURCE_EXHAUSTED status and PJRT/allocator "out of memory" text in
    the exception type or message (chained causes included one level)."""
    if exc is None:
        return False
    for e in (exc, exc.__cause__, exc.__context__):
        if e is None:
            continue
        text = f"{type(e).__name__}: {e}".lower()
        if any(marker in text for marker in _OOM_MARKERS):
            return True
    return False


def maybe_dump_oom(exc: BaseException, recorder=None,
                   directory: str | None = None) -> str | None:
    """If ``exc`` is OOM-shaped, write a postmortem bundle (reason
    ``resource_exhausted``) whose ``memory.json`` carries the ledger
    snapshot + watermark timeline, and stamp ``exc.bundle`` so the crash
    hooks don't dump a second near-identical bundle. Returns the bundle
    directory, or None when the exception is not an OOM."""
    if not is_oom(exc):
        return None
    if getattr(exc, "bundle", None) is not None:
        return exc.bundle  # already dumped (sentinel/hangwatch contract)
    from dsml_tpu.obs import flight_recorder

    rec = recorder if recorder is not None else flight_recorder.get_flight_recorder()
    bundle = rec.dump("resource_exhausted", exc=exc, directory=directory)
    try:
        exc.bundle = bundle
    except Exception:  # noqa: BLE001 — slotted/frozen exceptions
        pass
    return bundle
