"""Cluster observability plane: cross-process metric aggregation + trace
stitching.

PRs 3-4 built a strong *single-process* stack (registry, spans,
goodput/MFU, forensics) — but the system this repo reproduces is a
multi-process topology: a coordinator, N device servers, serving
replicas, chaos ``VirtualFleet`` subprocesses. Each of those owns a
disconnected registry; pod-scale tuning (the MLPerf TPU-pod recipe in
PAPERS.md) lives or dies on the CROSS-host view — which host straggles,
whether a wire op overlaps its device-side execution, what the fleet's
aggregate goodput is. This module is that view:

- :func:`snapshot` — one process's registry + Chrome trace, stamped with
  ``host``/``pid``/``role`` identity and a monotonic-clock reading on the
  SAME origin as the trace events' ``ts`` (so offsets computed for the
  snapshot align its spans too).
- :class:`ClusterAggregator` — collects snapshots (HTTP scrape of the
  existing ``start_metrics_server`` endpoint's ``/cluster.json``, gRPC
  pull/push over the ``comm/`` plumbing's ObsPlane service, or plain
  dicts/files), merges them (exact-sum counters, bucket-wise histogram
  merge), and exposes ONE Prometheus/JSONL exposition where every series
  carries ``host``/``pid``/``role`` labels plus ``<name>:fleet``
  aggregate series, fleet goodput, and a per-process straggler ranking.
- :func:`stitch_traces` — per-process Chrome traces merged into one
  chrome-loadable timeline with one lane (pid) per process, aligned by
  handshake-measured clock offsets (NTP-style midpoint) with a
  wall-clock fallback for offline snapshot files.

Merge semantics, the label schema, and the clock-alignment contract are
specified in ``docs/OBSERVABILITY.md`` § Cluster.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time

from dsml_tpu.obs import spans as _spans
from dsml_tpu.obs.registry import (
    Registry,
    _fmt_labels,
    _fmt_num,
    get_registry,
)
from dsml_tpu.obs.slo import STATUS_LEVELS, burn_rate

__all__ = [
    "SNAPSHOT_SCHEMA",
    "ClockSync",
    "ClusterAggregator",
    "current_role",
    "estimate_quantile",
    "merge_snapshots",
    "snapshot",
    "stitch_traces",
    "trace_summary",
    "validate_snapshot",
]

SNAPSHOT_SCHEMA = "dsml.obs.cluster/1"

# identity labels the aggregator stamps onto every merged series; a worker
# registry must not use them itself (the merge would silently shadow them)
IDENTITY_LABELS = ("host", "pid", "role")


def current_role(default: str = "worker") -> str:
    """This process's fleet role (``DSML_OBS_ROLE``, else ``default``).
    Conventional values: coordinator / device_server / trainer /
    decode_replica / chaos / bench."""
    return os.environ.get("DSML_OBS_ROLE", "") or default


def now_us() -> float:
    """Monotonic µs on the SAME origin as span trace events' ``ts`` —
    the snapshot clock and the trace clock must be one clock, or the
    stitcher's offsets would align the metrics but skew the spans."""
    return (time.perf_counter() - _spans.SpanTracer._t0) * 1e6


def snapshot(role: str | None = None, registry: Registry | None = None,
             tracer=None, with_trace: bool = True) -> dict:
    """One process's observable state, stamped with identity + clocks.

    The ``wall_s``/``mono_us`` pair is the offline clock handshake: two
    snapshots' offsets can always be estimated from wall clocks (coarse,
    NTP-disciplined hosts); a live scrape adds the precise RTT-midpoint
    handshake on top (:meth:`ClusterAggregator.add_scraped`)."""
    reg = registry if registry is not None else get_registry()
    trc = tracer if tracer is not None else _spans.get_tracer()
    snap = {
        "schema": SNAPSHOT_SCHEMA,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "role": role or current_role(),
        "wall_s": time.time(),
        "mono_us": now_us(),
        "enabled": reg.enabled,
        "metrics": reg.collect(),
    }
    if with_trace:
        snap["trace"] = trc.chrome_trace()
    return snap


@dataclasses.dataclass
class ClockSync:
    """A process clock's offset into the aggregator's monotonic timeline:
    ``t_agg_us = t_proc_us + offset_us``. ``rtt_us`` bounds the handshake
    error (the true offset lies within ±rtt/2 of the midpoint estimate);
    wall-clock fallbacks carry ``rtt_us=None`` — same-host processes share
    a wall clock, cross-host accuracy is NTP's."""

    offset_us: float
    rtt_us: float | None
    method: str  # "handshake" | "wall" | "identity"

    @classmethod
    def from_handshake(cls, t0_us: float, t1_us: float,
                       proc_mono_us: float) -> "ClockSync":
        """NTP-style single exchange: the aggregator read its clock at
        ``t0`` (request out) and ``t1`` (response in); the worker read
        ``proc_mono_us`` somewhere in between — assume the midpoint."""
        return cls(offset_us=(t0_us + t1_us) / 2.0 - proc_mono_us,
                   rtt_us=max(t1_us - t0_us, 0.0), method="handshake")

    @classmethod
    def from_wall(cls, snap: dict, ref_wall_s: float,
                  ref_mono_us: float) -> "ClockSync":
        """Fallback: map the snapshot's (wall, mono) pair onto the
        aggregator's. offset = what must be added to the process's mono
        reading so both clocks agree on the shared wall instant."""
        return cls(
            offset_us=(snap["wall_s"] - ref_wall_s) * 1e6
            + ref_mono_us - snap["mono_us"],
            rtt_us=None, method="wall",
        )


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------


def _series_key(rec: dict) -> tuple:
    return (rec["name"], tuple(sorted(rec.get("labels", {}).items())))


def _bounds_of(rec: dict) -> tuple:
    return tuple(b for b in rec["buckets"] if b != "+Inf")


def _noncumulative(rec: dict) -> list[int]:
    """Recover per-bucket counts (incl. the +Inf overflow) from the
    cumulative exposition."""
    bounds = _bounds_of(rec)
    cum = [rec["buckets"][b] for b in bounds] + [rec["buckets"]["+Inf"]]
    out, prev = [], 0
    for c in cum:
        out.append(c - prev)
        prev = c
    return out


def estimate_quantile(bounds: tuple, cum_counts: dict, q: float) -> float | None:
    """Quantile estimate from cumulative bucket counts (linear
    interpolation inside the straddling bucket, Prometheus
    ``histogram_quantile`` style). Used for fleet-level percentiles,
    where no raw sample tail survives the merge. Returns the top finite
    bound when the quantile lands in the +Inf overflow bucket."""
    total = cum_counts.get("+Inf", 0)
    if total <= 0:
        return None
    rank = q * total
    prev_cum, prev_bound = 0, 0.0
    for b in bounds:
        c = cum_counts[b]
        if c >= rank:
            inside = c - prev_cum
            frac = (rank - prev_cum) / inside if inside else 1.0
            return float(prev_bound + frac * (float(b) - prev_bound))
        prev_cum, prev_bound = c, float(b)
    return float(bounds[-1]) if bounds else None


class _MergedHist:
    __slots__ = ("bounds", "counts", "sum", "count", "conflict", "exemplars")

    def __init__(self, bounds: tuple):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.conflict = False  # a contributor's bounds didn't match
        self.exemplars: dict = {}  # bucket bound -> newest exemplar

    def add(self, rec: dict) -> bool:
        if _bounds_of(rec) != self.bounds:
            self.conflict = True
            return False
        for i, c in enumerate(_noncumulative(rec)):
            self.counts[i] += c
        self.sum += rec["sum"]
        self.count += rec["count"]
        # exemplars survive the merge (newest wall-clock wins per bucket):
        # a FLEET tail bucket still resolves to a concrete trace_id
        for bound, ex in (rec.get("exemplars") or {}).items():
            prev = self.exemplars.get(bound)
            if prev is None or ex.get("time", 0) >= prev.get("time", 0):
                self.exemplars[bound] = ex
        return True

    def cumulative(self) -> dict:
        out, running = {}, 0
        for b, c in zip(self.bounds, self.counts):
            running += c
            out[b] = running
        out["+Inf"] = running + self.counts[-1]
        return out


class MergedView:
    """The fleet-wide merge of N process snapshots.

    Two layers, one exposition:

    - *per-process series*: every worker series re-labeled with
      ``host``/``pid``/``role`` — the lossless layer; sums/rates computed
      downstream stay exact because nothing was pre-aggregated;
    - *fleet aggregates*: counters exact-summed, histograms merged
      bucket-wise (bounds must match — mismatches are kept per-process
      only and listed in ``notes``), exposed under ``<name>:fleet`` (the
      Prometheus recording-rule naming convention, so a fleet series can
      never be double-counted into a ``sum()`` over worker series).
      Gauges are NOT fleet-aggregated — a queue depth sums, a ratio
      means; picking silently would lie — the per-process layer plus
      :meth:`report`'s min/mean/max cover both readings.
    """

    def __init__(self):
        self.processes: list[dict] = []  # identity dicts, insertion order
        self._proc_series: list[dict] = []  # re-labeled per-process records
        self._fleet_counters: dict[tuple, float] = {}
        self._fleet_hists: dict[tuple, _MergedHist] = {}
        self._meta: dict[str, tuple] = {}  # name -> (type, help-less kind)
        self.notes: list[str] = []

    # -- ingest ------------------------------------------------------------

    def add_snapshot(self, snap: dict) -> None:
        validate_snapshot(snap)
        ident = {"host": str(snap["host"]), "pid": str(snap["pid"]),
                 "role": str(snap["role"])}
        self.processes.append(
            {**ident, "wall_s": snap["wall_s"], "mono_us": snap["mono_us"],
             "n_series": len(snap["metrics"])}
        )
        for rec in snap["metrics"]:
            labels = dict(rec.get("labels", {}))
            clash = set(labels) & set(IDENTITY_LABELS)
            if clash:
                # a worker label named "host" would be silently shadowed by
                # the identity stamp; surface it instead
                self.notes.append(
                    f"{rec['name']}: worker labels {sorted(clash)} shadowed "
                    "by identity labels"
                )
            self._meta[rec["name"]] = rec["type"]
            self._proc_series.append(
                {**rec, "labels": {**labels, **ident}}
            )
            key = _series_key(rec)
            if rec["type"] == "counter":
                self._fleet_counters[key] = (
                    self._fleet_counters.get(key, 0.0) + rec["value"]
                )
            elif rec["type"] == "histogram":
                merged = self._fleet_hists.get(key)
                if merged is None:
                    merged = self._fleet_hists[key] = _MergedHist(_bounds_of(rec))
                if not merged.add(rec):
                    self.notes.append(
                        f"{rec['name']}{dict(key[1])}: bucket bounds differ "
                        "across processes; fleet merge skipped (per-process "
                        "series retained)"
                    )

    # -- derived fleet metrics --------------------------------------------

    def _gauge_values(self, *names: str) -> list[tuple[dict, float]]:
        return [
            (rec["labels"], rec["value"])
            for rec in self._proc_series
            if rec["name"] in names and rec["type"] == "gauge"
        ]

    def fleet_goodput(self) -> float | None:
        """Mean of the per-process goodput gauges (``train_goodput`` /
        ``goodput_ratio``), one vote per process — each gauge is already
        a productive/wall RATIO for its whole process, so the unweighted
        mean is the fleet's "average fraction of wall spent training";
        per-process values stay in the exposition for weighted readings."""
        per_proc: dict[tuple, float] = {}
        for labels, v in self._gauge_values("train_goodput", "goodput_ratio"):
            per_proc[(labels["host"], labels["pid"])] = float(v)
        if not per_proc:
            return None
        return sum(per_proc.values()) / len(per_proc)

    def straggler_ranking(self, metric: str = "span_ms",
                          where: dict | None = None, q: float = 0.5,
                          multiplier: float = 2.0) -> list[dict]:
        """Per-process latency ranking from ``metric``'s per-process
        histograms, worst first. ``where`` filters on the metric's own
        labels (e.g. ``{"name": "wire_op"}``); ``q`` picks the quantile;
        a process above ``multiplier``× the fleet median is flagged
        ``straggler`` — the cross-host signal the MLPerf pod paper tunes
        on, which N disconnected registries cannot produce."""
        per_proc: dict[tuple, dict] = {}
        for rec in self._proc_series:
            if rec["name"] != metric or rec["type"] != "histogram":
                continue
            labels = rec["labels"]
            if where and any(labels.get(k) != str(v) for k, v in where.items()):
                continue
            key = (labels["host"], labels["pid"], labels["role"])
            slot = per_proc.setdefault(
                key, {"bounds": _bounds_of(rec), "counts": {}, "count": 0}
            )
            if slot["bounds"] != _bounds_of(rec):
                continue  # mixed-bound series within one process: skip
            for b, c in rec["buckets"].items():
                slot["counts"][b] = slot["counts"].get(b, 0) + c
            slot["count"] += rec["count"]
        rows = []
        for (host, pid, role), slot in per_proc.items():
            est = estimate_quantile(slot["bounds"], slot["counts"], q)
            if est is None:
                continue
            rows.append({"host": host, "pid": pid, "role": role,
                         "value_ms": round(est, 6), "count": slot["count"]})
        rows.sort(key=lambda r: r["value_ms"], reverse=True)
        if rows:
            vals = sorted(r["value_ms"] for r in rows)
            median = vals[len(vals) // 2]
            for r in rows:
                r["straggler"] = bool(r["value_ms"] > multiplier * median
                                      and len(rows) > 1)
        return rows

    def slo_status(self) -> dict:
        """Fleet-wide SLO accounting from the merged ``slo_*`` series
        (written per process by ``obs.slo.SLOTracker`` — the serving
        router's request accounting). Counters merge EXACTLY, so per-class
        per-SLI compliance and the all-time burn are true fleet numbers;
        the rolling multi-window status is per-process state, so the
        fleet status is the WORST process's (max of the
        ``slo_burn_status`` gauges — a paging replica pages the fleet)."""
        classes: dict[str, dict] = {}

        def cls_row(name: str) -> dict:
            return classes.setdefault(
                name, {"objective": None, "requests": 0, "good_requests": 0,
                       "sli": {}, "status": "ok"}
            )

        for (name, labels), v in self._fleet_counters.items():
            ld = dict(labels)
            if name == "slo_requests_total" and "slo" in ld:
                cls_row(ld["slo"])["requests"] = int(v)
            elif name == "slo_good_total" and "slo" in ld:
                cls_row(ld["slo"])["good_requests"] = int(v)
            elif name == "slo_sli_total" and {"slo", "sli", "verdict"} <= set(ld):
                sli = cls_row(ld["slo"])["sli"].setdefault(
                    ld["sli"], {"good": 0, "bad": 0}
                )
                sli[ld["verdict"]] = sli.get(ld["verdict"], 0) + int(v)
        levels = STATUS_LEVELS  # one ladder — obs.slo owns the encoding
        names = {v: k for k, v in levels.items()}
        for rec in self._proc_series:
            if rec["type"] != "gauge":
                continue
            ld = rec["labels"]
            if rec["name"] == "slo_objective" and ld.get("slo") in classes:
                classes[ld["slo"]]["objective"] = float(rec["value"])
            elif rec["name"] == "slo_burn_status" and ld.get("slo") in classes:
                row = classes[ld["slo"]]
                level = int(rec["value"])
                if level > levels[row["status"]]:
                    row["status"] = names.get(level, "page")
                sli = row["sli"].setdefault(ld.get("sli", "?"), {})
                worst = sli.get("status", "ok")
                if level > levels.get(worst, 0):
                    sli["status"] = names.get(level, "page")
        for row in classes.values():
            obj = row["objective"]
            for sli in row["sli"].values():
                total = sli.get("good", 0) + sli.get("bad", 0)
                if total:
                    sli["compliance"] = round(sli.get("good", 0) / total, 6)
                    if obj is not None and obj < 1.0:
                        sli["burn_total"] = round(
                            burn_rate(sli.get("bad", 0) / total, obj), 4
                        )
                sli.setdefault("status", "ok")
        return classes

    # -- exposition --------------------------------------------------------

    def collect(self) -> list[dict]:
        """JSON snapshot: per-process series + fleet aggregates."""
        out = list(self._proc_series)
        for (name, labels), v in sorted(self._fleet_counters.items()):
            out.append({"name": f"{name}:fleet", "type": "counter",
                        "labels": dict(labels), "value": v})
        for (name, labels), h in sorted(self._fleet_hists.items()):
            if h.conflict:
                continue
            rec = {"name": f"{name}:fleet", "type": "histogram",
                   "labels": dict(labels), "buckets": h.cumulative(),
                   "sum": h.sum, "count": h.count}
            if h.exemplars:
                rec["exemplars"] = dict(h.exemplars)
            out.append(rec)
        g = self.fleet_goodput()
        if g is not None:
            out.append({"name": "fleet_goodput", "type": "gauge",
                        "labels": {}, "value": round(g, 6)})
        out.append({"name": "fleet_processes", "type": "gauge", "labels": {},
                    "value": len(self.processes)})
        return out

    def to_jsonl(self) -> str:
        now = time.time()
        return "\n".join(
            json.dumps({"time": now, **rec}) for rec in self.collect()
        )

    def to_prometheus_text(self) -> str:
        """ONE text exposition for the whole fleet (format 0.0.4): worker
        series labeled {host,pid,role}, fleet aggregates as
        ``<name>:fleet``, plus the derived fleet gauges."""
        lines, last_family = [], None
        # group by family: per-process records arrive interleaved across
        # snapshots, and the exposition format wants one TYPE header with
        # every series of that family under it
        records = sorted(self.collect(),
                         key=lambda r: (r["name"], sorted(r["labels"].items())))
        for rec in records:
            base = rec["name"].removesuffix(":fleet")
            kind = self._meta.get(base, rec["type"])
            if rec["name"] != last_family:
                lines.append(f"# TYPE {rec['name']} {kind}")
                last_family = rec["name"]
            pairs = rec["labels"]
            if rec["type"] == "histogram":
                for b, c in rec["buckets"].items():
                    lines.append(
                        f"{rec['name']}_bucket"
                        f"{_fmt_labels({**pairs, 'le': b})} {c}"
                    )
                lines.append(
                    f"{rec['name']}_sum{_fmt_labels(pairs)} {_fmt_num(rec['sum'])}"
                )
                lines.append(
                    f"{rec['name']}_count{_fmt_labels(pairs)} {rec['count']}"
                )
            else:
                lines.append(
                    f"{rec['name']}{_fmt_labels(pairs)} {_fmt_num(rec['value'])}"
                )
        return "\n".join(lines) + ("\n" if lines else "")

    def report(self) -> dict:
        """Machine-readable fleet summary (the bench/CI artifact)."""
        gauges: dict[str, list[float]] = {}
        for rec in self._proc_series:
            if rec["type"] == "gauge":
                gauges.setdefault(rec["name"], []).append(float(rec["value"]))
        gauge_rows = {
            name: {"min": min(v), "mean": sum(v) / len(v), "max": max(v),
                   "n": len(v)}
            for name, v in sorted(gauges.items())
        }
        # the memory ledger's fleet view (docs/OBSERVABILITY.md § Memory
        # ledger): per-host headroom/unattributed/claimed merged min/mean/
        # max — gauges are NEVER summed (two hosts' headroom doesn't add),
        # so the min row is the fleet's binding chip and the max row its
        # roomiest. Keyed without the hbm_ prefix but WITH the series'
        # non-identity labels: pooling hbm_measured_bytes by bare name
        # would take a min over bytes_in_use readings and a max over
        # bytes_limit — cross-kind garbage. Empty when no process
        # exported ledger gauges.
        mem_vals: dict[str, list[float]] = {}
        for rec in self._proc_series:
            if rec["type"] != "gauge" or not rec["name"].startswith("hbm_"):
                continue
            extra = {k: v for k, v in rec["labels"].items()
                     if k not in IDENTITY_LABELS}
            key = rec["name"][len("hbm_"):]
            if extra:
                key += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(extra.items())) + "}"
            mem_vals.setdefault(key, []).append(float(rec["value"]))
        memory_rows = {
            key: {"min": min(v), "mean": sum(v) / len(v), "max": max(v),
                  "n": len(v)}
            for key, v in sorted(mem_vals.items())
        }
        return {
            "schema": "dsml.obs.cluster_report/1",
            "processes": self.processes,
            "n_series": len(self._proc_series),
            "fleet_goodput": self.fleet_goodput(),
            "stragglers": self.straggler_ranking(),
            "gauges": gauge_rows,
            "memory": memory_rows,
            "slo": self.slo_status(),
            "notes": self.notes,
        }


def validate_snapshot(snap) -> None:
    """Schema + shape check shared by every ingest path."""
    if not isinstance(snap, dict) or snap.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"not a cluster snapshot (schema="
            f"{snap.get('schema') if isinstance(snap, dict) else type(snap).__name__!r}; "
            f"expected {SNAPSHOT_SCHEMA!r})"
        )
    missing = {"host", "pid", "role", "wall_s", "mono_us", "metrics"} - set(snap)
    if missing:
        raise ValueError(f"cluster snapshot missing keys {sorted(missing)}")
    if not isinstance(snap["metrics"], list):
        raise ValueError("cluster snapshot 'metrics' must be a list")


def merge_snapshots(snaps: list[dict]) -> MergedView:
    view = MergedView()
    for s in snaps:
        view.add_snapshot(s)
    return view


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------


def stitch_traces(snaps: list[dict],
                  syncs: dict[int, ClockSync] | None = None) -> dict:
    """Merge per-process Chrome traces into one chrome-loadable timeline.

    Each process becomes one pid lane (named ``role host:pid`` via ``M``
    metadata events, coordinator lanes sorted first). Event timestamps are
    shifted onto a shared timeline by each snapshot's :class:`ClockSync`
    (``syncs`` keyed by snapshot index); snapshots without one fall back
    to the wall-clock offset against the FIRST snapshot. The merged
    timeline is re-zeroed so it starts near ts=0.
    """
    if not snaps:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    syncs = syncs or {}
    ref = snaps[0]
    events: list[dict] = []
    used_pids: set[int] = set()
    for i, snap in enumerate(snaps):
        sync = syncs.get(i)
        if sync is None:
            sync = (ClockSync(0.0, None, "identity") if snap is ref
                    else ClockSync.from_wall(snap, ref["wall_s"],
                                             ref["mono_us"]))
        # one lane per PROCESS: real pid when unique, else remapped (two
        # hosts can reuse a pid; chrome would fold their lanes together)
        pid = int(snap["pid"])
        while pid in used_pids:
            pid += 100_000
        used_pids.add(pid)
        role = str(snap["role"])
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{role} {snap['host']}:{snap['pid']}"},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
            "args": {"sort_index": 0 if role == "coordinator" else i + 1},
        })
        for e in (snap.get("trace") or {}).get("traceEvents", []):
            events.append({**e, "pid": pid, "ts": e["ts"] + sync.offset_us})
    timed = [e for e in events if e["ph"] != "M"]
    t0 = min((e["ts"] for e in timed), default=0.0)
    for e in timed:
        e["ts"] -= t0
    timed.sort(key=lambda e: e["ts"])
    meta = [e for e in events if e["ph"] == "M"]
    return {"traceEvents": meta + timed, "displayTimeUnit": "ms"}


def trace_summary(trace: dict) -> dict:
    """Per-request causal chains from a (stitched or single-process)
    Chrome trace: {trace_id: {pids, names, flow}} for every event tagged
    with a ``trace_id`` arg (request spans, instants, flow events —
    ``obs.spans.TraceContext`` propagation). ``flow`` counts the flow
    phases seen (``s``/``t``/``f``) — a fully linked request shows one
    start, ≥1 step, one end; ``pids`` is the set of process lanes the
    request's events landed in (the ≥3-process acceptance reads this)."""
    out: dict[str, dict] = {}
    for e in trace.get("traceEvents", []):
        tid = (e.get("args") or {}).get("trace_id")
        if not tid:
            continue
        row = out.setdefault(
            tid, {"pids": set(), "names": [], "flow": {}, "n_events": 0}
        )
        row["pids"].add(e.get("pid"))
        row["n_events"] += 1
        if e.get("ph") in ("s", "t", "f"):
            row["flow"][e["ph"]] = row["flow"].get(e["ph"], 0) + 1
        elif e.get("ph") in ("B", "i") and e.get("name") not in row["names"]:
            row["names"].append(e["name"])
    for row in out.values():
        row["pids"] = sorted(row["pids"])
    return out


# ---------------------------------------------------------------------------
# aggregator: scrape (HTTP + gRPC pull), push, artifacts
# ---------------------------------------------------------------------------


class ClusterAggregator:
    """Collects snapshots from a fleet and produces the merged artifacts.

    Three ingest paths (mixable):

    - :meth:`scrape` — HTTP GET of a worker's ``/cluster.json``
      (``obs.start_metrics_server``), with the RTT-midpoint clock
      handshake measured around the request;
    - :meth:`pull` — the same over the ``comm/`` gRPC plumbing's
      ObsPlane service (device servers and the coordinator attach it to
      the grpc.Server they already run — one port, one channel);
    - :meth:`add` — a snapshot dict/file pushed or loaded offline
      (wall-clock alignment).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._snaps: list[dict] = []
        self._syncs: dict[int, ClockSync] = {}

    # -- ingest ------------------------------------------------------------

    def add(self, snap: dict, sync: ClockSync | None = None) -> None:
        """Raises ``ValueError`` on a malformed snapshot AT INGEST — one
        bad worker (version skew, a stray client) must cost one rejected
        snapshot, not blow up ``merged()``/``stitched_trace()`` at
        artifact-write time with every good snapshot's data."""
        validate_snapshot(snap)
        with self._lock:
            idx = len(self._snaps)
            self._snaps.append(snap)
            if sync is not None:
                self._syncs[idx] = sync

    def add_file(self, path: str) -> None:
        with open(path) as f:
            self.add(json.load(f))

    def scrape(self, base_url: str, timeout: float = 10.0) -> dict:
        """GET ``<base_url>/cluster.json`` with the clock handshake."""
        import urllib.request

        url = base_url.rstrip("/") + "/cluster.json"
        t0 = now_us()
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            body = resp.read()
        t1 = now_us()
        snap = json.loads(body)
        self.add(snap, ClockSync.from_handshake(t0, t1, snap["mono_us"]))
        return snap

    def pull(self, address: str, timeout: float = 10.0) -> dict:
        """ObsPlane.PullSnapshot over a gRPC channel (clock handshake
        measured around the RPC)."""
        import grpc

        from dsml_tpu.comm import rpc as comm_rpc

        channel = grpc.insecure_channel(address)
        try:
            stub = comm_rpc.obs_stub(channel)
            t0 = now_us()
            body = stub.PullSnapshot(b"{}", timeout=timeout)
            t1 = now_us()
        finally:
            channel.close()
        snap = json.loads(body)
        self.add(snap, ClockSync.from_handshake(t0, t1, snap["mono_us"]))
        return snap

    # -- outputs -----------------------------------------------------------

    def merged(self) -> MergedView:
        with self._lock:
            snaps = list(self._snaps)
        return merge_snapshots(snaps)

    def stitched_trace(self) -> dict:
        with self._lock:
            snaps, syncs = list(self._snaps), dict(self._syncs)
        return stitch_traces(snaps, syncs)

    def to_prometheus_text(self) -> str:
        return self.merged().to_prometheus_text()

    def report(self) -> dict:
        rep = self.merged().report()
        with self._lock:
            rep["clock_sync"] = {
                i: {"offset_us": round(s.offset_us, 3),
                    "rtt_us": None if s.rtt_us is None else round(s.rtt_us, 3),
                    "method": s.method}
                for i, s in self._syncs.items()
            }
        return rep

    def write_artifacts(self, out_dir: str) -> dict:
        """Write the merged exposition, stitched trace, and report; returns
        the paths (the CI/bench artifact set)."""
        os.makedirs(out_dir, exist_ok=True)
        paths = {
            "prometheus": os.path.join(out_dir, "cluster_metrics.prom"),
            "trace": os.path.join(out_dir, "cluster_trace.json"),
            "report": os.path.join(out_dir, "cluster_report.json"),
        }
        with open(paths["prometheus"], "w") as f:
            f.write(self.to_prometheus_text())
        with open(paths["trace"], "w") as f:
            json.dump(self.stitched_trace(), f)
        with open(paths["report"], "w") as f:
            json.dump(self.report(), f, indent=2, sort_keys=True)
        return paths


# ---------------------------------------------------------------------------
# worker side: the ObsPlane gRPC servicer + aggregator push
# ---------------------------------------------------------------------------


class ObsServicer:
    """Worker-side ObsPlane: serves this process's snapshot over the same
    grpc.Server the worker already runs for its gpu_sim service (attach
    with ``rpc.add_obs_servicer``). Raw-JSON payloads — the reference
    proto stays byte-for-byte untouched; a reference peer simply never
    calls this extension service."""

    def __init__(self, role: str, registry: Registry | None = None,
                 tracer=None):
        self.role = role
        self._registry = registry
        self._tracer = tracer

    def PullSnapshot(self, request: bytes, context) -> bytes:  # noqa: N802
        opts = json.loads(request or b"{}")
        snap = snapshot(role=self.role, registry=self._registry,
                        tracer=self._tracer,
                        with_trace=bool(opts.get("trace", True)))
        return json.dumps(snap).encode()

    def PushSnapshot(self, request: bytes, context) -> bytes:  # noqa: N802
        import grpc

        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "this ObsPlane endpoint only serves PullSnapshot")


class AggregatorServicer:
    """Aggregator-side ObsPlane: accepts worker pushes."""

    def __init__(self, aggregator: ClusterAggregator):
        self.aggregator = aggregator

    def PushSnapshot(self, request: bytes, context) -> bytes:  # noqa: N802
        import grpc

        try:
            snap = json.loads(request)
        except ValueError:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "PushSnapshot payload is not JSON")
        try:
            # push has no handshake (the worker can't read our clock); wall
            # alignment happens at stitch time against the reference snapshot
            self.aggregator.add(snap)
        except ValueError as e:
            # reject THIS push; never poison the aggregator's artifact run
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return json.dumps({"ok": True, "agg_mono_us": now_us()}).encode()

    def PullSnapshot(self, request: bytes, context) -> bytes:  # noqa: N802
        import grpc

        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "aggregators accept PushSnapshot only")


def serve_aggregator(aggregator: ClusterAggregator, port: int = 0,
                     host: str = "127.0.0.1"):
    """Boot a standalone aggregator endpoint workers can push to.
    Returns a handle with ``.address`` and ``.stop()``."""
    from concurrent import futures as _futures

    import grpc

    from dsml_tpu.comm import rpc as comm_rpc

    server = grpc.server(_futures.ThreadPoolExecutor(max_workers=4))
    comm_rpc.add_obs_servicer(AggregatorServicer(aggregator), server)
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()

    class _Handle:
        address = f"{host}:{bound}"

        @staticmethod
        def stop(grace: float = 0.2) -> None:
            server.stop(grace)

    return _Handle()


def push_snapshot(address: str, role: str | None = None,
                  registry: Registry | None = None,
                  with_trace: bool = True, timeout: float = 10.0) -> dict:
    """Worker→aggregator push over the comm/ plumbing: one shot, returns
    the aggregator's ack. For workers behind NAT/one-way topologies where
    the aggregator cannot scrape."""
    import grpc

    from dsml_tpu.comm import rpc as comm_rpc

    snap = snapshot(role=role, registry=registry, with_trace=with_trace)
    channel = grpc.insecure_channel(address)
    try:
        stub = comm_rpc.obs_stub(channel)
        ack = stub.PushSnapshot(json.dumps(snap).encode(), timeout=timeout)
    finally:
        channel.close()
    return json.loads(ack)


# ---------------------------------------------------------------------------
# demo CLI: the 3-process proof (also the CI artifact generator)
# ---------------------------------------------------------------------------

_DEMO_WORKER_FLAG = "--serve-one-device"


def _demo_worker_main(device_id: int) -> None:
    """Subprocess body: ONE device server with obs enabled + the ObsPlane
    attached; prints its address as a JSON line, then serves until stdin
    closes (the parent's exit tears us down)."""
    import sys

    from dsml_tpu import obs
    from dsml_tpu.comm.device_server import serve_device

    obs.enable(forensics=False)
    handle = serve_device(device_id, mem_size=0x100000)
    print(json.dumps({"address": handle.address, "pid": os.getpid()}),
          flush=True)
    sys.stdin.read()  # parent closes the pipe → exit
    handle.stop()


def run_cluster_demo(out_dir: str, n_devices: int = 2,
                     payload_floats: int = 1024) -> dict:
    """The zero→aha proof: coordinator (this process) + ``n_devices``
    device-server SUBPROCESSES, one all-reduce over the wire, then scrape
    every process over the ObsPlane and write the merged exposition +
    stitched trace + report into ``out_dir``. Returns the report with the
    artifact paths attached. Used by CI and ``bench.py --section
    cluster``'s round-trip row; the acceptance test drives the same
    function."""
    import subprocess
    import sys

    import numpy as np

    from dsml_tpu import obs
    from dsml_tpu.comm.coordinator import CoordinatorConfig, serve_coordinator

    obs.enable(forensics=False)
    env = {**os.environ, "DSML_OBS": "1", "JAX_PLATFORMS": "cpu",
           "DSML_OBS_ROLE": "device_server"}
    procs, addrs = [], []
    try:
        for i in range(n_devices):
            p = subprocess.Popen(
                [sys.executable, "-m", "dsml_tpu.obs.cluster",
                 _DEMO_WORKER_FLAG, str(i + 1)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
                text=True,
            )
            procs.append(p)
        for p in procs:
            line = p.stdout.readline()
            addrs.append(json.loads(line)["address"])
        coordinator = serve_coordinator(
            config=CoordinatorConfig(health_interval_s=0.5,
                                     probe_timeout_s=2.0)
        )
        try:
            rt = coordinator.runtime
            comm = rt.comm_init(n_devices, addrs)
            data = np.arange(payload_floats, dtype=np.float32)
            for info in comm.devices:
                rt.memcpy_h2d(info.device_id, 0x1000, data.tobytes())
            rt.all_reduce_ring(comm.comm_id, data.nbytes, dtype="float32")
            agg = ClusterAggregator()
            agg.add(snapshot(role="coordinator"),
                    ClockSync(0.0, 0.0, "identity"))
            for addr in addrs:
                agg.pull(addr)
            paths = agg.write_artifacts(out_dir)
            report = agg.report()
            report["artifacts"] = paths
            report["n_processes"] = 1 + n_devices
            return report
        finally:
            coordinator.stop()
    finally:
        for p in procs:
            try:
                p.stdin.close()
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m dsml_tpu.obs.cluster",
        description="cluster obs demo: 3-process merged exposition + "
        "stitched trace",
    )
    ap.add_argument("--demo", metavar="OUT_DIR",
                    help="run coordinator + 2 device-server subprocesses, "
                    "write merged artifacts into OUT_DIR")
    ap.add_argument(_DEMO_WORKER_FLAG, type=int, default=None,
                    metavar="DEVICE_ID", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.serve_one_device is not None:
        _demo_worker_main(args.serve_one_device)
        return 0
    if not args.demo:
        ap.print_help()
        return 2
    report = run_cluster_demo(args.demo)
    print(json.dumps({k: report[k] for k in
                      ("n_processes", "n_series", "artifacts", "notes")},
                     indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
