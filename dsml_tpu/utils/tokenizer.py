"""Byte-level BPE tokenizer — trained, saved, and loaded with zero network.

The reference has no text pipeline at all (its only model consumes MNIST
pixels, ``DSML/client/client.go:270-350``); this framework's LM families
need one, and pretrained tokenizer assets cannot be downloaded in the
deployment environment. So the tokenizer is its own component: classic
byte-level BPE (the GPT-2 algorithm — Sennrich et al. merges over a byte
base vocabulary) trained on any corpus, serialized to a single JSON file.

Design points:

- **Byte base vocabulary** (ids 0-255): any UTF-8 input round-trips exactly
  — no unknown-token path, no normalization of any kind (NFC/NFD inputs
  round-trip as given). ``decode(encode(s)) == s`` for arbitrary valid
  Unicode ``s`` (pinned in tests, including emoji/CJK and decomposed
  accents); the one exception is unpaired surrogates — not valid text —
  which encode as "?" instead of raising.
- **Pre-tokenization** splits text into word-ish pieces (leading-space
  convention like GPT-2: ``" the"`` is one piece, so merges never cross
  word boundaries and frequent words become single tokens). The piece
  pattern covers every character class, which is what makes the round-trip
  exact by construction.
- **Training** is the standard weighted-pair-count loop over the UNIQUE
  pieces (not the raw stream), deterministic: ties break on the
  lexicographically smallest pair so the same corpus always yields the
  same merges.
- **Encoding** applies merges by rank with a per-piece cache (the hot path
  is a dict lookup per word, not a merge loop).

Usage::

    tok = BPETokenizer.train(corpus_text, vocab_size=2048)
    ids = tok.encode("Attention is all you need.")
    tok.save("data/bpe_2048.json");  tok2 = BPETokenizer.load(...)
"""

from __future__ import annotations

import json
import re
from collections import Counter

import numpy as np

__all__ = ["BPETokenizer", "padded_vocab"]


def padded_vocab(n: int, tp: int = 1) -> int:
    """Model vocab for a trained tokenizer of ``n`` ids: rounded up to a
    multiple of lcm(8, tp). The fixed 8 makes the padding REPRODUCIBLE
    across runs that shard differently (a checkpoint trained at tp=4 must
    restore under tp=1 serving — both sides compute the same number for
    any tp DIVIDING 8, i.e. 1/2/4/8, the realistic TPU mesh sizes), keeps
    the embedding divisible for vocab-sharding, and rounds the unembed
    matmul toward MXU tiles. The padded rows are never produced by
    encode() and never sampled from a trained model (their logits only see
    gradient through softmax mass). Any OTHER tp (3, 5, 6, 7, or > 8)
    pads to lcm(8, tp) — correct for training, but the SAME tp is then
    required at serving; cross-tp portability holds only within
    {1, 2, 4, 8}."""
    m = 8
    while m % tp:  # lcm(8, tp) for the tp > 8 case
        m += 8
    return -(-n // m) * m

# every char lands in exactly one alternative: space-prefixed letter runs,
# space-prefixed digit runs, space-prefixed symbol runs (underscore counts
# as a symbol: \w contains it, so [^\w\s] alone would DROP it and break the
# round-trip on snake_case text), then bare whitespace runs (a greedy \s+
# keeps the final space before a word for the " word" alternatives only
# when it is the single separating space — longer gaps stay whitespace
# tokens)
_PIECE_RE = re.compile(r" ?[^\W\d_]+| ?\d+| ?(?:[^\w\s]|_)+|\s+", re.UNICODE)


def _pieces(text: str) -> list[str]:
    return _PIECE_RE.findall(text)


class BPETokenizer:
    """A trained byte-level BPE vocabulary: ``merges`` is the ordered list
    of (left_id, right_id) pairs; merge i produces token id ``256 + i``.
    ``eos_id``/``bos_id`` (optional) are appended after the merge tokens."""

    def __init__(self, merges: list[tuple[int, int]], specials: tuple[str, ...] = ("<|eos|>",)):
        self.merges = [tuple(m) for m in merges]
        self.specials = tuple(specials)
        self._ranks = {pair: i for i, pair in enumerate(self.merges)}
        # token id -> bytes (specials decode to their literal text)
        self._bytes: list[bytes] = [bytes([b]) for b in range(256)]
        for a, b in self.merges:
            if a >= len(self._bytes) or b >= len(self._bytes):
                raise ValueError(f"merge ({a}, {b}) references an undefined token")
            self._bytes.append(self._bytes[a] + self._bytes[b])
        self._special_ids = {
            s: 256 + len(self.merges) + i for i, s in enumerate(self.specials)
        }
        self._cache: dict[bytes, list[int]] = {}

    # ---- vocabulary ----------------------------------------------------------

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges) + len(self.specials)

    @property
    def eos_id(self) -> int | None:
        return self._special_ids.get("<|eos|>")

    def special_id(self, token: str) -> int:
        return self._special_ids[token]

    def token_bytes(self, tid: int) -> bytes:
        if tid < 256 + len(self.merges):
            return self._bytes[tid]
        return self.specials[tid - 256 - len(self.merges)].encode("utf-8")

    # ---- train ---------------------------------------------------------------

    @classmethod
    def train(
        cls,
        text: str,
        vocab_size: int = 2048,
        specials: tuple[str, ...] = ("<|eos|>",),
        min_pair_freq: int = 2,
    ) -> "BPETokenizer":
        """Learn ``vocab_size - 256 - len(specials)`` merges from ``text``.
        Deterministic for a fixed corpus (ties break on the smaller pair).
        Stops early when no pair reaches ``min_pair_freq`` — a tiny corpus
        yields a smaller vocab rather than degenerate merges."""
        n_merges = vocab_size - 256 - len(specials)
        if n_merges < 0:
            raise ValueError(
                f"vocab_size={vocab_size} cannot hold the 256 byte tokens "
                f"plus {len(specials)} specials"
            )
        piece_freq = Counter(_pieces(text))
        # unique pieces as mutable symbol sequences + their frequencies
        # (errors="replace" mirrors encode(): a stray unpaired surrogate in
        # the corpus trains as "?" instead of crashing the trainer)
        words: list[list[int]] = []
        freqs: list[int] = []
        for piece, f in piece_freq.items():
            words.append(list(piece.encode("utf-8", errors="replace")))
            freqs.append(f)

        # incremental pair bookkeeping: recounting every pair after every
        # merge is O(merges x corpus) and dominates training time; instead
        # keep global counts plus an occurs-in index and touch only the
        # words that actually contain the merged pair (the standard fast
        # BPE trainer shape — ~10x on the repo prose corpus)
        counts: Counter = Counter()
        where: dict[tuple[int, int], set[int]] = {}
        for wi, (w, f) in enumerate(zip(words, freqs)):
            for pair in zip(w, w[1:]):
                counts[pair] += f
                where.setdefault(pair, set()).add(wi)

        merges: list[tuple[int, int]] = []
        for _ in range(n_merges):
            if not counts:
                break
            # deterministic argmax: highest count, then smallest pair
            pair, best = min(counts.items(), key=lambda kv: (-kv[1], kv[0]))
            if best < min_pair_freq:
                break
            new_id = 256 + len(merges)
            merges.append(pair)
            a, b = pair
            for wi in list(where.get(pair, ())):
                w, f = words[wi], freqs[wi]
                # retract this word's old pairs, rewrite, re-add new pairs
                for p in zip(w, w[1:]):
                    counts[p] -= f
                    if counts[p] <= 0:
                        del counts[p]
                    s = where.get(p)
                    if s is not None:
                        s.discard(wi)
                        if not s:
                            del where[p]
                i, out = 0, []
                while i < len(w):
                    if i + 1 < len(w) and w[i] == a and w[i + 1] == b:
                        out.append(new_id)
                        i += 2
                    else:
                        out.append(w[i])
                        i += 1
                w[:] = out
                for p in zip(w, w[1:]):
                    counts[p] += f
                    where.setdefault(p, set()).add(wi)
        return cls(merges, specials)

    # ---- encode / decode -----------------------------------------------------

    def _bpe(self, piece: bytes) -> list[int]:
        cached = self._cache.get(piece)
        if cached is not None:
            return cached
        w = list(piece)
        while len(w) > 1:
            # the lowest-rank (earliest-learned) adjacent pair merges first —
            # the same order training created them
            best_rank, best_i = None, -1
            for i, pair in enumerate(zip(w, w[1:])):
                r = self._ranks.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            w[best_i : best_i + 2] = [256 + best_rank]
        if len(self._cache) < 1 << 20:  # bound the cache on adversarial input
            self._cache[piece] = w
        return w

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for piece in _pieces(text):
            # errors="replace": an unpaired surrogate (not valid Unicode
            # text) must not crash the tokenizer — it encodes as "?"
            # (str.encode's replacement), the one documented exception to
            # the exact round-trip
            ids.extend(self._bpe(piece.encode("utf-8", errors="replace")))
        return ids

    def encode_array(self, text: str) -> np.ndarray:
        return np.asarray(self.encode(text), np.int32)

    def decode(self, ids) -> str:
        out = bytearray()
        n_plain = 256 + len(self.merges)
        for tid in np.asarray(ids).reshape(-1).tolist():
            if tid < 0 or tid >= self.vocab_size:
                raise ValueError(f"token id {tid} outside vocab {self.vocab_size}")
            out += self.token_bytes(int(tid)) if tid < n_plain else self.specials[
                tid - n_plain
            ].encode("utf-8")
        return out.decode("utf-8", errors="replace")

    # ---- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {
                    "format": "dsml_bpe_v1",
                    "merges": [list(m) for m in self.merges],
                    "specials": list(self.specials),
                },
                f,
            )

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != "dsml_bpe_v1":
            raise ValueError(f"{path!r} is not a dsml_bpe_v1 tokenizer file")
        return cls([tuple(m) for m in blob["merges"]], tuple(blob["specials"]))
