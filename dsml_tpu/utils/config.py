"""Dataclass-backed configuration with CLI-flag and JSON-file overrides.

The reference has *no* config layer at all — every knob is a hard-coded
constant (ports in ``DSML/cmd/gpu_device_server/main.go:13-23``, hyperparams
in ``DSML/client/client.go:22-33``, health interval in
``gpu_coordinator_service/gpu_coordinator_server.go:57``; see SURVEY.md §5.6).
This module closes that gap: every process in dsml_tpu (device host,
coordinator, trainer) is configured through a ``Config`` subclass that can be

- constructed programmatically (tests),
- overridden from CLI flags (``--lr 0.01 --mesh.dp 4``), and
- loaded from a JSON file (``--config path.json``).

Nested configs use dotted flag names. Types are enforced from the dataclass
annotations; ``bool`` flags accept true/false/1/0.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import typing
from dataclasses import field as _dc_field
from typing import Any, Sequence

__all__ = ["Config", "field", "parse_cli", "ConfigError", "env_float", "env_int"]


def env_float(name: str, default: float) -> float:
    """``float(os.environ[name])`` with the default on unset/garbage — the
    shared parser behind the ``DSML_*`` runtime knobs (stream TTL/stall,
    migration deadlines); one implementation so a parsing fix cannot
    diverge between subsystems."""
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    """Integer twin of :func:`env_float`."""
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def field(default=dataclasses.MISSING, *, default_factory=dataclasses.MISSING, help: str = ""):
    """Dataclass field with an attached ``help`` string for CLI usage text."""
    kwargs: dict[str, Any] = {"metadata": {"help": help}}
    if default is not dataclasses.MISSING:
        kwargs["default"] = default
    if default_factory is not dataclasses.MISSING:
        kwargs["default_factory"] = default_factory
    return _dc_field(**kwargs)


class ConfigError(ValueError):
    pass


@dataclasses.dataclass
class Config:
    """Base class for all dsml_tpu configs. Subclass with typed fields."""

    # ---- construction helpers -------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Config":
        """Build a config from a (possibly nested) plain dict."""
        kwargs = {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for key, value in d.items():
            if key not in fields:
                raise ConfigError(f"{cls.__name__}: unknown config key {key!r}")
            ftype = _resolve_type(cls, fields[key])
            if isinstance(ftype, type) and issubclass(ftype, Config) and isinstance(value, dict):
                value = ftype.from_dict(value)
            kwargs[key] = value
        return cls(**kwargs)

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    # ---- overrides ------------------------------------------------------------

    def override(self, dotted: str, raw: Any) -> None:
        """Set ``a.b.c`` to ``raw`` (string values are coerced to field type)."""
        obj: Any = self
        parts = dotted.split(".")
        for p in parts[:-1]:
            if not (dataclasses.is_dataclass(obj) and hasattr(obj, p)):
                raise ConfigError(f"unknown config path {dotted!r} (at {p!r})")
            obj = getattr(obj, p)
        if not dataclasses.is_dataclass(obj) or isinstance(obj, type):
            raise ConfigError(f"unknown config path {dotted!r} (not a nested config)")
        leaf = parts[-1]
        fields = {f.name: f for f in dataclasses.fields(obj)}
        if leaf not in fields:
            raise ConfigError(f"unknown config path {dotted!r} (at {leaf!r})")
        ftype = _resolve_type(type(obj), fields[leaf])
        setattr(obj, leaf, _coerce(raw, ftype, dotted))

    # ---- CLI ------------------------------------------------------------------

    @classmethod
    def parse_args(cls, argv: Sequence[str] | None = None) -> "Config":
        """Parse ``--flag value`` / ``--flag=value`` argv into a config.

        Special flags: ``--config FILE`` loads a JSON file first (CLI flags
        then override it); ``--help`` prints generated usage and exits.
        """
        argv = list(sys.argv[1:] if argv is None else argv)
        if "--help" in argv or "-h" in argv:
            print(cls.usage())
            sys.exit(0)

        pairs: list[tuple[str, str]] = []
        i = 0
        cfg_file = None
        while i < len(argv):
            tok = argv[i]
            if not tok.startswith("--"):
                raise ConfigError(f"unexpected argument {tok!r} (flags are --name value)")
            name = tok[2:]
            if "=" in name:
                name, value = name.split("=", 1)
            else:
                if i + 1 >= len(argv):
                    raise ConfigError(f"flag --{name} is missing a value")
                value = argv[i + 1]
                i += 1
            if name == "config":
                cfg_file = value
            else:
                pairs.append((name, value))
            i += 1

        cfg = cls.from_file(cfg_file) if cfg_file else cls()
        for name, value in pairs:
            cfg.override(name, value)
        return cfg

    @classmethod
    def usage(cls, prefix: str = "") -> str:
        lines = [] if prefix else [f"{cls.__name__} flags:"]
        for f in dataclasses.fields(cls):
            ftype = _resolve_type(cls, f)
            dotted = f"{prefix}{f.name}"
            if isinstance(ftype, type) and issubclass(ftype, Config):
                lines.append(ftype.usage(prefix=f"{dotted}."))
            else:
                default = (
                    f.default
                    if f.default is not dataclasses.MISSING
                    else (f.default_factory() if f.default_factory is not dataclasses.MISSING else None)
                )
                help_txt = f.metadata.get("help", "") if f.metadata else ""
                lines.append(f"  --{dotted} ({_type_name(ftype)}, default={default!r})  {help_txt}")
        return "\n".join(lines)


def parse_cli(cls: type, argv: Sequence[str] | None = None):
    return cls.parse_args(argv)


# ---- internals ----------------------------------------------------------------


def _resolve_type(cls: type, f: dataclasses.Field):
    hints = typing.get_type_hints(cls)
    return hints.get(f.name, f.type)


def _type_name(t) -> str:
    return getattr(t, "__name__", str(t))


_TRUE = {"1", "true", "yes", "on"}
_FALSE = {"0", "false", "no", "off"}


def _coerce(raw: Any, ftype, dotted: str):
    import types

    if not isinstance(raw, str):
        return raw
    origin = typing.get_origin(ftype)
    if origin is types.UnionType:  # PEP 604 `T | None`
        origin = typing.Union
    if origin in (list, tuple, typing.Union):
        args = typing.get_args(ftype)
        if origin is typing.Union:  # Optional[T] / T | None
            non_none = [a for a in args if a is not type(None)]
            if raw.lower() in ("none", "null"):
                return None
            return _coerce(raw, non_none[0], dotted) if non_none else raw
        elem = args[0] if args else str
        items = [s for s in raw.split(",") if s != ""]
        seq = [_coerce(s, elem, dotted) for s in items]
        return tuple(seq) if origin is tuple else seq
    if ftype is bool:
        low = raw.lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        raise ConfigError(f"--{dotted}: cannot parse {raw!r} as bool")
    if ftype in (int, float, str):
        try:
            if ftype is int:
                try:
                    return int(raw)  # plain decimal, incl. zero-padded "08"
                except ValueError:
                    return int(raw, 0)  # hex/octal/binary (0x3000 memory sizes)
            return ftype(raw)
        except ValueError as e:
            raise ConfigError(f"--{dotted}: {e}") from e
    return raw
