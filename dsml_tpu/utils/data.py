"""Datasets: MNIST IDX parsing, sharded batching, synthetic workloads.

The reference ships gzipped IDX files and parses them in Go
(``DSML/client/client.go:270-350``). Its mirror is missing the 60k-image
training blob (``/root/reference/.MISSING_LARGE_BLOBS``, SURVEY.md §8.11), so
:func:`load_mnist` transparently falls back to carving a train/test split out
of the 10k test set (and can augment it with pixel shifts to recover headroom)
— real train images are used automatically when present at
``data/mnist/train-images-idx3-ubyte.gz``.

Also provides :func:`synthetic_classification` (benchmark workloads never
bottlenecked on disk) and :func:`shard_batches`, the host-side data-parallel
batch iterator (per-device shards laid out for a ``dp`` mesh axis).
"""

from __future__ import annotations

import gzip
import hashlib
import os
import struct
from dataclasses import dataclass

import numpy as np

from dsml_tpu.utils.logging import get_logger

log = get_logger("data")

_IMAGES_MAGIC = 2051
_LABELS_MAGIC = 2049


def _read_idx(path: str) -> np.ndarray:
    """Parse one (gzipped) IDX file (images or labels). Decoding goes through
    the native C++ runtime when built (dsml_tpu/runtime/native), with a pure
    numpy fallback."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        blob = f.read()
    try:
        from dsml_tpu.runtime import native

        if native.available():
            data, _ = native.idx_parse(blob)
            return data
    except Exception as e:  # noqa: BLE001 — any native hiccup falls back
        log.warning("native IDX parse failed (%s); numpy fallback", e)
    magic, count = struct.unpack(">II", blob[:8])
    if magic == _IMAGES_MAGIC:
        rows, cols = struct.unpack(">II", blob[8:16])
        return np.frombuffer(blob, np.uint8, count * rows * cols, 16).reshape(count, rows, cols)
    if magic == _LABELS_MAGIC:
        return np.frombuffer(blob, np.uint8, count, 8)
    raise ValueError(f"{path}: unknown IDX magic {magic}")


@dataclass
class Dataset:
    train_x: np.ndarray  # [N, ...] float32 in [0, 1]
    train_y: np.ndarray  # [N] int32
    test_x: np.ndarray
    test_y: np.ndarray

    @property
    def n_train(self) -> int:
        return self.train_x.shape[0]


def load_mnist(
    data_dir: str = "data/mnist",
    flatten: bool = True,
    augment_fallback: bool = True,
    holdout: int = 2000,
) -> Dataset:
    """Load MNIST; fall back to a t10k-derived split when the 60k train
    images are absent (see module docstring)."""
    train_images = os.path.join(data_dir, "train-images-idx3-ubyte.gz")
    test_x = _read_idx(os.path.join(data_dir, "t10k-images-idx3-ubyte.gz"))
    test_y = _read_idx(os.path.join(data_dir, "t10k-labels-idx1-ubyte.gz"))
    if os.path.exists(train_images):
        train_x = _read_idx(train_images)
        train_y = _read_idx(os.path.join(data_dir, "train-labels-idx1-ubyte.gz"))
    else:
        log.warning(
            "train-images blob absent (stripped from the reference mirror); "
            "splitting t10k %d/%d train/test%s",
            test_x.shape[0] - holdout, holdout, " with shift augmentation" if augment_fallback else "",
        )
        train_x, train_y = test_x[:-holdout], test_y[:-holdout]
        test_x, test_y = test_x[-holdout:], test_y[-holdout:]
        if augment_fallback:
            train_x, train_y = _augment_shifts(train_x, train_y)

    def prep(x):
        x = x.astype(np.float32) / 255.0
        return x.reshape(x.shape[0], -1) if flatten else x[..., None]

    return Dataset(prep(train_x), train_y.astype(np.int32), prep(test_x), test_y.astype(np.int32))


def _augment_shifts(x: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """5× the data with ±1-pixel translations (cheap, label-preserving)."""
    shifted = [x]
    for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
        s = np.roll(x, (dy, dx), axis=(1, 2))
        # zero the wrapped edge
        if dy == 1:
            s[:, 0, :] = 0
        elif dy == -1:
            s[:, -1, :] = 0
        if dx == 1:
            s[:, :, 0] = 0
        elif dx == -1:
            s[:, :, -1] = 0
        shifted.append(s)
    return np.concatenate(shifted), np.tile(y, len(shifted))


def synthetic_classification(
    n: int, features: int, classes: int = 10, seed: int = 0, image_shape: tuple | None = None
) -> Dataset:
    """Linearly-separable-ish synthetic data; loss must drop fast on it, which
    makes it the convergence canary for trainer tests and benchmarks."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, features)).astype(np.float32) * 2.0
    y = rng.integers(0, classes, size=n).astype(np.int32)
    x = centers[y] + rng.standard_normal((n, features)).astype(np.float32)
    if image_shape is not None:
        x = x.reshape(n, *image_shape)
    split = max(1, int(n * 0.9))
    return Dataset(x[:split], y[:split], x[split:], y[split:])


def prefetch_batches(iterator, depth: int = 2):
    """Run ``iterator`` in a background thread, keeping up to ``depth``
    batches ready — host-side batch assembly (shuffle-gather, the pure-numpy
    cost of :func:`shard_batches`) overlaps device compute instead of
    serializing with it. The reference's client assembled batches inline on
    the training thread (``client.go:592-603``)."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        # never block forever: if the consumer abandoned the generator
        # (exception mid-epoch), the worker must exit, not pin the thread
        # and `depth` batches of host memory
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in iterator:
                if not put(item):
                    return
            put(_END)
        except BaseException as e:  # noqa: BLE001 — surface on the consumer side
            put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def shard_batches(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    seed: int | None = None,
    drop_remainder: bool = True,
    native: bool | None = None,
):
    """Yield (x_batch, y_batch) host batches, shuffled per epoch. The batch is
    the GLOBAL batch; the mesh sharding (``P('dp')`` on axis 0) splits it
    across data-parallel ranks at dispatch — the real data sharding the
    reference lacked (its 'DP' shipped identical full batches everywhere,
    SURVEY.md §2.3).

    ``native`` routes the (large) x-row gather through the C++
    background-thread loader (``runtime.native.NativePrefetcher``) so it
    overlaps device compute at the native layer; ``None`` auto-detects,
    ``False`` forces the numpy path. Values are identical either way
    (tests pin it) — labels stay a numpy gather (tiny)."""
    n = x.shape[0]
    idx = np.arange(n)
    if seed is not None:
        np.random.default_rng(seed).shuffle(idx)
    end = (n // batch_size) * batch_size if drop_remainder else n
    n_full = end // batch_size
    # SETUP only inside the try: once batches start yielding, a native
    # error must propagate — falling back mid-stream would restart the
    # epoch from batch 0 and silently feed duplicated data
    pf = batch_idx = None
    if native is not False and n_full > 0:
        try:
            from dsml_tpu.runtime import native as nat

            if nat.available():
                batch_idx = idx[: n_full * batch_size].reshape(
                    n_full, batch_size
                ).astype(np.int32)
                pf = nat.NativePrefetcher(x, batch_idx, depth=2)
        except Exception:
            if native:  # explicitly requested — don't silently degrade
                raise
            pf = None
    if native and pf is None:
        raise RuntimeError(
            "native=True but the native runtime is unavailable (no compiler?)"
        )
    if pf is not None:
        for b, xb in enumerate(pf):
            yield xb, y[batch_idx[b]]
        if not drop_remainder and end > n_full * batch_size:
            sel = idx[n_full * batch_size : end]
            yield x[sel], y[sel]
        return
    for start in range(0, end, batch_size):
        sel = idx[start : start + batch_size]
        yield x[sel], y[sel]


def lm_window_batches(
    tokens: np.ndarray,
    seq_len: int,
    batch_size: int,
    seed: int = 0,
    steps: int | None = None,
):
    """Yield (x, y) next-token LM batches: ``batch_size`` random windows of
    ``seq_len`` tokens each, y = x shifted one token left. The language-model
    counterpart of :func:`shard_batches` (same contract: GLOBAL batch, the
    mesh's ``P('dp')`` placement shards it); composes with
    :func:`prefetch_batches` so window assembly overlaps device compute.
    ``steps=None`` streams forever (training loops bound their own step
    count)."""
    tokens = np.asarray(tokens)
    if len(tokens) < seq_len + 1:
        raise ValueError(f"corpus of {len(tokens)} tokens too small for seq_len={seq_len}")
    rng = np.random.default_rng(seed)
    produced = 0
    while steps is None or produced < steps:
        # a start s is valid iff s + seq_len + 1 <= len (y reaches one past
        # x), so the exclusive high is len - seq_len — the last token of the
        # corpus IS reachable as a target
        starts = rng.integers(0, len(tokens) - seq_len, size=batch_size)
        x = np.stack([tokens[s : s + seq_len] for s in starts])
        y = np.stack([tokens[s + 1 : s + seq_len + 1] for s in starts])
        yield x.astype(np.int32), y.astype(np.int32)
        produced += 1


def carve_lm_eval_split(
    tokens: np.ndarray, seq_len: int, batch_size: int, frac: float = 0.05
) -> tuple[np.ndarray, np.ndarray | None]:
    """Split a token stream into (train, eval) tails for held-out perplexity.
    Returns ``(tokens, None)`` — eval disabled — when the corpus is too small
    to carve ``frac`` (or one batch of windows) without starving training."""
    tokens = np.asarray(tokens)
    carve = max((seq_len + 1) * batch_size, int(len(tokens) * frac), seq_len + 2)
    if carve > len(tokens) // 4 or len(tokens) - carve <= seq_len + 1:
        return tokens, None
    split = len(tokens) - carve
    return tokens[:split], tokens[split:]


# text-rich stdlib + dependency modules whose docstrings form the on-disk
# English prose pool for build_prose_corpus (importing any of these is
# side-effect free; missing ones are skipped)
_PROSE_MODULES = (
    "argparse", "ast", "asyncio", "calendar", "codecs", "collections",
    "concurrent.futures", "configparser", "contextlib", "csv", "datetime",
    "decimal", "difflib", "dis", "doctest", "email", "enum", "fractions",
    "functools", "gettext", "heapq", "html", "http", "imaplib", "inspect",
    "ipaddress", "itertools", "json", "logging", "mailbox", "math",
    "multiprocessing", "optparse", "os", "pathlib", "pdb", "pickle",
    "pickletools", "platform", "plistlib", "pprint", "profile", "pydoc",
    "queue", "random", "re", "sched", "secrets", "selectors", "shlex",
    "shutil", "smtplib", "socket", "socketserver", "sqlite3", "ssl",
    "statistics", "string", "subprocess", "tarfile", "tempfile", "textwrap",
    "threading", "timeit", "traceback", "turtle", "typing", "unittest",
    "urllib.parse", "urllib.request", "uuid", "warnings", "wave", "weakref",
    "xml.dom", "xml.etree.ElementTree", "zipfile", "zoneinfo",
    "numpy", "numpy.linalg", "numpy.fft", "numpy.random",
    # ML-library docstrings (all baked into this image, BSD/Apache): they
    # roughly double the prose pool, which the 16k-vocab BPE row needs —
    # 1.6 MB of text cannot support 16k merges (most pairs fall under
    # min_pair_freq and the trainer early-stops far short)
    "jax", "jax.numpy", "jax.scipy.linalg", "flax.linen", "optax",
    "einops", "chex", "torch", "torch.nn", "torch.optim", "torch.utils.data",
    "transformers",
)


def build_prose_corpus(max_bytes: int = 4_000_000) -> str:
    """Assemble a REAL English prose corpus from what's guaranteed on disk:
    the repo's own markdown docs plus the docstrings of Python's stdlib and
    numpy (PSF/BSD licensed). This is the no-network fallback for a
    loss-goes-down-on-real-text demonstration (VERDICT r2 item 5: the
    bench's LM rows trained on synthetic random tokens, which supports
    throughput claims but no quality claim): the statistics are genuine
    natural language — skewed toward technical register, which the
    provenance label says out loud.

    Deterministic: fixed module list, sorted member traversal, first-seen
    dedup (inherited/re-exported docstrings appear once)."""
    import importlib
    import inspect

    parts: list[str] = []
    seen: set[bytes] = set()

    def add(text: str | None):
        if text and len(text) > 40:
            # stable digest, NOT builtin hash(): str hashing is salted per
            # process, so a hash() collision could drop different texts in
            # different runs and break the determinism promised above
            h = hashlib.sha1(text.encode("utf-8", "replace")).digest()
            if h not in seen:
                seen.add(h)
                parts.append(text)

    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    for name in sorted(os.listdir(root)):
        if name.endswith(".md"):
            try:
                with open(os.path.join(root, name), encoding="utf-8") as f:
                    add(f.read())
            except OSError:
                continue

    # import EVERYTHING first, then traverse: the ML libraries lazily
    # import each other's internals (flax/transformers pull jax submodules
    # in), which ADDS attributes to modules earlier in this list — a
    # traversal interleaved with imports would see different membership on
    # a second call and break the determinism promised below
    mods = {}
    for modname in _PROSE_MODULES:
        try:
            mods[modname] = importlib.import_module(modname)
        except Exception:  # noqa: BLE001 — any unimportable module is skipped
            continue

    total = lambda: sum(len(p) for p in parts)  # noqa: E731
    for modname in _PROSE_MODULES:
        if total() >= max_bytes:
            break
        mod = mods.get(modname)
        if mod is None:
            continue
        add(inspect.getdoc(mod))
        for _, obj in sorted(vars(mod).items()):
            if not (inspect.isclass(obj) or inspect.isfunction(obj)):
                continue
            # `or ""`: C-extension objects may carry __module__ = None
            if not (getattr(obj, "__module__", "") or "").startswith(
                modname.split(".")[0]
            ):
                continue  # re-exports would duplicate across modules
            add(inspect.getdoc(obj))
            if inspect.isclass(obj):
                for _, member in sorted(vars(obj).items()):
                    doc = getattr(member, "__doc__", None)
                    if isinstance(doc, str):
                        add(doc)
    return "\n\n".join(parts)[:max_bytes]


def load_text_corpus(
    path: str | None = None, max_bytes: int = 4_000_000
) -> tuple[np.ndarray, str]:
    """(byte-level token array uint8, provenance string) for LM training on
    REAL text. Priority: explicit ``path`` (missing file raises — a typo
    must not silently train on the wrong corpus) → ``<repo>/data/corpus.txt``
    (the documented drop-in hook for a user corpus, e.g. TinyStories;
    repo-root-anchored so the hook works from any cwd) →
    :func:`build_prose_corpus`. Byte-level (vocab 256) so no tokenizer
    asset is needed."""
    if path is not None:
        if not os.path.exists(path):
            raise FileNotFoundError(f"corpus file {path!r} does not exist")
        with open(path, "rb") as f:
            raw = f.read(max_bytes)
        return np.frombuffer(raw, np.uint8).copy(), f"user corpus {path}"
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    hook = os.path.join(root, "data", "corpus.txt")
    if os.path.exists(hook):
        with open(hook, "rb") as f:
            raw = f.read(max_bytes)
        return np.frombuffer(raw, np.uint8).copy(), "data/corpus.txt (user-provided)"
    text = build_prose_corpus(max_bytes)
    return (
        np.frombuffer(text.encode("utf-8"), np.uint8).copy(),
        "repo markdown docs + Python stdlib/numpy/ML-library docstrings "
        "(real English prose, technical register; byte-level tokens)",
    )
