"""Structured logging for multi-process runs.

The reference logs with Go's stdlib ``log.Printf`` (SURVEY.md §5.5). Here every
process (coordinator, device host, trainer) gets a namespaced logger whose
records carry the process role and — when running under ``jax.distributed`` —
the host index, so interleaved multi-host logs stay attributable.
"""

from __future__ import annotations

import collections
import logging
import os
import sys
import threading
import time

_CONFIGURED = False

RING_CAPACITY = 512  # last-N log records kept for postmortem bundles


class _Formatter(logging.Formatter):
    def formatTime(self, record, datefmt=None):  # noqa: N802 (logging API)
        ct = time.localtime(record.created)
        return time.strftime("%Y/%m/%d %H:%M:%S", ct)


def configure(level: int = logging.INFO, stream=None) -> None:
    """Install the dsml_tpu log format on the root ``dsml`` logger once."""
    global _CONFIGURED
    root = logging.getLogger("dsml")
    if _CONFIGURED:
        root.setLevel(level)
        return
    handler = logging.StreamHandler(stream or sys.stderr)
    role = os.environ.get("DSML_ROLE", "")
    role_tag = f" [{role}]" if role else ""
    handler.setFormatter(_Formatter(f"%(asctime)s{role_tag} %(name)s: %(message)s"))
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    """Return a logger under the ``dsml`` namespace, configuring on first use."""
    configure(level=getattr(logging, os.environ.get("DSML_LOG_LEVEL", "INFO").upper(), logging.INFO))
    return logging.getLogger(f"dsml.{name}")


class RingBufferHandler(logging.Handler):
    """Keeps the last ``capacity`` records as structured dicts, so a
    postmortem bundle carries the log tail even when stdout/stderr are
    already gone (redirected, truncated, or swallowed by the scheduler).

    ``obs.enable()`` installs one on the ``dsml`` root logger; the flight
    recorder snapshots :meth:`records` into ``log_tail.jsonl``."""

    def __init__(self, capacity: int = RING_CAPACITY):
        super().__init__(level=logging.DEBUG)
        self._records: collections.deque = collections.deque(
            maxlen=max(int(capacity), 1)
        )
        self._ring_lock = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            rec = {
                "t": round(record.created, 6),
                "level": record.levelname,
                "logger": record.name,
                "msg": record.getMessage(),
            }
            if record.exc_info and record.exc_info[1] is not None:
                rec["exc"] = repr(record.exc_info[1])[:500]
        except Exception:  # noqa: BLE001 — a bad record must not recurse
            return
        with self._ring_lock:
            self._records.append(rec)

    def records(self) -> list[dict]:
        with self._ring_lock:
            return list(self._records)

    def clear(self) -> None:
        with self._ring_lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._ring_lock:
            return len(self._records)


_ring_handler: RingBufferHandler | None = None
_ring_lock = threading.Lock()


def install_ring_handler(capacity: int = RING_CAPACITY) -> RingBufferHandler:
    """Attach (once) a :class:`RingBufferHandler` to the ``dsml`` root
    logger and return it; idempotent — repeated calls return the existing
    handler (capacity is fixed by the first call)."""
    global _ring_handler
    with _ring_lock:
        if _ring_handler is None:
            configure(level=getattr(
                logging, os.environ.get("DSML_LOG_LEVEL", "INFO").upper(),
                logging.INFO,
            ))
            _ring_handler = RingBufferHandler(capacity)
            logging.getLogger("dsml").addHandler(_ring_handler)
        return _ring_handler


def uninstall_ring_handler() -> None:
    global _ring_handler
    with _ring_lock:
        if _ring_handler is not None:
            logging.getLogger("dsml").removeHandler(_ring_handler)
            _ring_handler = None


def get_ring_handler() -> RingBufferHandler | None:
    """The installed ring handler, or ``None`` (flight-recorder probe)."""
    return _ring_handler
