"""Structured logging for multi-process runs.

The reference logs with Go's stdlib ``log.Printf`` (SURVEY.md §5.5). Here every
process (coordinator, device host, trainer) gets a namespaced logger whose
records carry the process role and — when running under ``jax.distributed`` —
the host index, so interleaved multi-host logs stay attributable.
"""

from __future__ import annotations

import logging
import os
import sys
import time

_CONFIGURED = False


class _Formatter(logging.Formatter):
    def formatTime(self, record, datefmt=None):  # noqa: N802 (logging API)
        ct = time.localtime(record.created)
        return time.strftime("%Y/%m/%d %H:%M:%S", ct)


def configure(level: int = logging.INFO, stream=None) -> None:
    """Install the dsml_tpu log format on the root ``dsml`` logger once."""
    global _CONFIGURED
    root = logging.getLogger("dsml")
    if _CONFIGURED:
        root.setLevel(level)
        return
    handler = logging.StreamHandler(stream or sys.stderr)
    role = os.environ.get("DSML_ROLE", "")
    role_tag = f" [{role}]" if role else ""
    handler.setFormatter(_Formatter(f"%(asctime)s{role_tag} %(name)s: %(message)s"))
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    """Return a logger under the ``dsml`` namespace, configuring on first use."""
    configure(level=getattr(logging, os.environ.get("DSML_LOG_LEVEL", "INFO").upper(), logging.INFO))
    return logging.getLogger(f"dsml.{name}")
