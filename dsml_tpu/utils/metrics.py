"""Training metrics + terminal progress, at parity with the reference client.

The reference reports per-epoch average loss and accuracy
(``DSML/client/client.go:650-652``), a final test accuracy (``:500-501``), and
draws per-epoch terminal progress bars via schollz/progressbar
(``client.go:584-590``; SURVEY.md §5.5). ``EpochMetrics``/``ProgressBar``
reproduce that surface; ``MetricsLogger`` adds the structured record the
reference lacked (JSON-lines history usable by tests and benchmarks).
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field


@dataclass
class EpochMetrics:
    """Running mean loss + accuracy over one epoch."""

    loss_sum: float = 0.0
    correct: int = 0
    seen: int = 0
    batches: int = 0

    def update(self, loss: float, correct: int, batch_size: int) -> None:
        self.loss_sum += float(loss)
        self.correct += int(correct)
        self.seen += int(batch_size)
        self.batches += 1

    @property
    def avg_loss(self) -> float:
        return self.loss_sum / max(self.batches, 1)

    @property
    def accuracy(self) -> float:
        return self.correct / max(self.seen, 1)

    def summary(self, epoch: int) -> str:
        # Same shape as the reference's per-epoch log line (client.go:650-652).
        return (
            f"Epoch {epoch}: Average Loss = {self.avg_loss:.4f}, "
            f"Accuracy = {self.accuracy * 100:.2f}%"
        )


class ProgressBar:
    """Minimal terminal progress bar (stand-in for schollz/progressbar)."""

    def __init__(self, total: int, desc: str = "", width: int = 30, stream=None, enabled: bool | None = None):
        self.total = max(total, 1)
        self.desc = desc
        self.width = width
        self.n = 0
        self.stream = stream or sys.stderr
        self.enabled = self.stream.isatty() if enabled is None else enabled
        self._t0 = time.monotonic()

    def update(self, k: int = 1) -> None:
        self.n += k
        if not self.enabled:
            return
        frac = min(self.n / self.total, 1.0)
        filled = int(frac * self.width)
        bar = "=" * filled + ">" + " " * (self.width - filled)
        rate = self.n / max(time.monotonic() - self._t0, 1e-9)
        self.stream.write(f"\r{self.desc} [{bar}] {self.n}/{self.total} ({rate:.0f}/s)")
        if frac >= 1.0:
            self.stream.write("\n")
        self.stream.flush()

    def close(self) -> None:
        if self.enabled and self.n < self.total:
            self.stream.write("\n")
            self.stream.flush()


class MetricsLogger:
    """Append-only JSON-lines metrics history with wall-clock timestamps."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.records: list[dict] = []

    def log(self, **kv) -> dict:
        rec = {"time": time.time(), **kv}
        self.records.append(rec)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec

    def last(self, **match) -> dict | None:
        for rec in reversed(self.records):
            if all(rec.get(k) == v for k, v in match.items()):
                return rec
        return None
