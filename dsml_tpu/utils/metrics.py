"""Training metrics + terminal progress, at parity with the reference client.

The reference reports per-epoch average loss and accuracy
(``DSML/client/client.go:650-652``), a final test accuracy (``:500-501``), and
draws per-epoch terminal progress bars via schollz/progressbar
(``client.go:584-590``; SURVEY.md §5.5). ``EpochMetrics``/``ProgressBar``
reproduce that surface. ``MetricsLogger`` — the structured JSON-lines
record the reference lacked — now lives in the observability subsystem
(``dsml_tpu.obs.export``, where it gained size-capped rotation) and is
re-exported here so existing imports keep working.
"""

from __future__ import annotations

import sys
import time

from dsml_tpu.obs.export import MetricsLogger  # noqa: F401 — compat re-export


class EpochMetrics:
    """Running mean loss + accuracy over one epoch."""

    def __init__(self):
        self.loss_sum = 0.0
        self.correct = 0
        self.seen = 0
        self.batches = 0

    def update(self, loss: float, correct: int, batch_size: int) -> None:
        self.loss_sum += float(loss)
        self.correct += int(correct)
        self.seen += int(batch_size)
        self.batches += 1

    @property
    def avg_loss(self) -> float:
        return self.loss_sum / max(self.batches, 1)

    @property
    def accuracy(self) -> float:
        return self.correct / max(self.seen, 1)

    def summary(self, epoch: int) -> str:
        # Same shape as the reference's per-epoch log line (client.go:650-652).
        return (
            f"Epoch {epoch}: Average Loss = {self.avg_loss:.4f}, "
            f"Accuracy = {self.accuracy * 100:.2f}%"
        )


class ProgressBar:
    """Minimal terminal progress bar (stand-in for schollz/progressbar).

    TTY-aware: on an interactive stream it redraws in place with ``\\r``;
    on a non-interactive stream (pytest, CI logs, piped output) it stays
    silent until the bar completes/closes, then emits ONE newline-
    terminated summary line — line-per-epoch logs instead of a wall of
    carriage returns. ``enabled=False`` silences it entirely."""

    def __init__(self, total: int, desc: str = "", width: int = 30, stream=None,
                 enabled: bool | None = None):
        self.total = max(total, 1)
        self.desc = desc
        self.width = width
        self.n = 0
        self.stream = stream or sys.stderr
        self.enabled = True if enabled is None else enabled
        self.interactive = bool(getattr(self.stream, "isatty", lambda: False)())
        self._t0 = time.monotonic()
        self._summarized = False
        self._last_filled = -1

    def update(self, k: int = 1) -> None:
        self.n += k
        if not self.enabled:
            return
        frac = min(self.n / self.total, 1.0)
        if not self.interactive:
            if frac >= 1.0:
                self._summary_line()
            return
        filled = int(frac * self.width)
        if filled == self._last_filled and frac < 1.0:
            return  # redraw only when the bar visibly moves (host-side noise)
        self._last_filled = filled
        bar = "=" * filled + ">" + " " * (self.width - filled)
        rate = self.n / max(time.monotonic() - self._t0, 1e-9)
        self.stream.write(f"\r{self.desc} [{bar}] {self.n}/{self.total} ({rate:.0f}/s)")
        if frac >= 1.0:
            self.stream.write("\n")
        self.stream.flush()

    def _summary_line(self) -> None:
        if self._summarized:
            return
        self._summarized = True
        rate = self.n / max(time.monotonic() - self._t0, 1e-9)
        self.stream.write(f"{self.desc} {self.n}/{self.total} ({rate:.0f}/s)\n")
        self.stream.flush()

    def close(self) -> None:
        if not self.enabled:
            return
        if not self.interactive:
            self._summary_line()
        elif self.n < self.total:
            self.stream.write("\n")
            self.stream.flush()
