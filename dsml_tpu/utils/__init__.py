"""Utility layer: config/flags, logging, metrics, data, checkpoint, tracing."""

from dsml_tpu.utils.config import Config, field, parse_cli  # noqa: F401
from dsml_tpu.utils.logging import get_logger  # noqa: F401
