"""Forward-compat shims so the framework runs on older jax (0.4.x).

The codebase targets the current jax surface (``jax.shard_map`` with
``check_vma``, ``jax.lax.axis_size``, ``jax.set_mesh``); the container pins
jax 0.4.37, where those live under older names or don't exist. ``install()``
grafts the missing names onto the installed jax IN TERMS OF its own
primitives — on a new-enough jax every branch is a no-op, so the shim
evaporates the day the pin moves.

Installed from ``dsml_tpu/__init__`` (every framework import path) and from
``tests/conftest.py`` (tests that call ``jax.shard_map`` directly before
importing any ``dsml_tpu`` module).

What is NOT shimmed: ``jax.typeof(...).vma`` (varying-manual-axes tracking,
the 1F1B pipeline schedule's foundation) has no 0.4.x equivalent — the 1F1B
paths raise on old jax rather than silently computing wrong gradients.
"""

from __future__ import annotations

import contextlib

_installed = False


def install() -> None:
    """Idempotently graft missing new-jax names onto the installed jax."""
    global _installed
    if _installed:
        return
    _installed = True

    import jax
    from jax import lax

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            # inside shard_map/pmap a psum of the Python constant 1 folds to
            # the static axis size (an int), which is exactly what callers
            # use for trace-time schedule decisions
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, check_rep=None, **kwargs):
            # check_vma (new name) ⇒ check_rep (old name). The framework
            # passes check_vma=False everywhere except 1F1B; both map 1:1.
            if check_rep is None:
                check_rep = True if check_vma is None else bool(check_vma)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep,
                              **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax, "set_mesh"):
        @contextlib.contextmanager
        def set_mesh(mesh):
            # old jax has no global-mesh context; every framework shard_map
            # names its mesh explicitly, so entering the context is enough
            with mesh:
                yield mesh

        jax.set_mesh = set_mesh
