"""Forward-compat shims so the framework runs on older jax (0.4.x).

The codebase targets the current jax surface (``jax.shard_map`` with
``check_vma``, ``jax.lax.axis_size``, ``jax.set_mesh``); the container pins
jax 0.4.37, where those live under older names or don't exist. ``install()``
grafts the missing names onto the installed jax IN TERMS OF its own
primitives — on a new-enough jax every branch is a no-op, so the shim
evaporates the day the pin moves.

Installed from ``dsml_tpu/__init__`` (every framework import path) and from
``tests/conftest.py`` (tests that call ``jax.shard_map`` directly before
importing any ``dsml_tpu`` module).

``jax.typeof`` / ``lax.pcast`` (varying-manual-axes tracking, which the
1F1B pipeline schedule uses to keep scan-carry types stable) are shimmed to
the 0.4.x semantics of ``check_rep=False``: there IS no vma tracking, every
per-shard value is implicitly varying, so ``typeof(x).vma`` reports every
axis (making ``_lift``'s "which axes are missing" computation the empty
set) and ``pcast`` is the identity. Collective transposes are exact on
0.4.x under ``check_rep=False`` — psum transposes to psum — which the 1F1B
gradient-parity test pins against a single-device reference.
"""

from __future__ import annotations

import contextlib

_installed = False


def install() -> None:
    """Idempotently graft missing new-jax names onto the installed jax."""
    global _installed
    if _installed:
        return
    _installed = True

    import jax
    from jax import lax

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            # inside shard_map/pmap a psum of the Python constant 1 folds to
            # the static axis size (an int), which is exactly what callers
            # use for trace-time schedule decisions
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, check_rep=None, **kwargs):
            # check_vma (new name) ⇒ check_rep (old name) — EXCEPT that
            # check_vma=True programs (the 1F1B schedule's per-tick vjps
            # with internal collectives) are exactly what 0.4.x's
            # replication checker cannot validate: it predates pcast/vma
            # and rejects them spuriously. Old jax runs them unchecked;
            # the 1F1B gradient-parity test pins that the VALUES agree.
            if check_rep is None:
                check_rep = False
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep,
                              **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax, "typeof"):
        # consumers that must compensate for the missing vma transpose
        # bookkeeping (models.gpt2.train_grads_1f1b_spmd's seed scaling)
        # key off this flag rather than sniffing jax versions
        jax._dsml_shimmed_vma = True

        class _AvalView:
            """Minimal stand-in for the new-jax aval ``typeof`` returns:
            delegates to the real 0.4.x aval, except ``.vma`` reports
            EVERY bound axis name — under old shard_map there is no
            replication tracking, so "varying over all mesh axes" is the
            honest type and makes the 1F1B ``_lift`` helper a no-op."""

            __slots__ = ("_aval",)

            def __init__(self, aval):
                self._aval = aval

            @property
            def vma(self):
                from jax._src.core import unsafe_get_axis_names

                return frozenset(
                    n for n in unsafe_get_axis_names() if isinstance(n, str)
                )

            def __getattr__(self, name):
                return getattr(self._aval, name)

        def typeof(x):
            from jax.core import get_aval

            return _AvalView(get_aval(x))

        jax.typeof = typeof

    if not hasattr(lax, "pcast"):
        def pcast(x, axis_name, *, to=None, **_kw):
            # no vma tracking on 0.4.x ⇒ values are already "varying";
            # casting is the identity on values
            del axis_name, to
            return x

        lax.pcast = pcast

    if not hasattr(jax, "set_mesh"):
        @contextlib.contextmanager
        def set_mesh(mesh):
            # old jax has no global-mesh context; every framework shard_map
            # names its mesh explicitly, so entering the context is enough
            with mesh:
                yield mesh

        jax.set_mesh = set_mesh
