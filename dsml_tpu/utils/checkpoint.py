"""Checkpoint/resume compat front-end over ``dsml_tpu.checkpoint``.

The reference has NO checkpointing at all (SURVEY.md §5.4: weights live in
client RAM and as opaque device bytes; a crash loses the run). This module
keeps the original :class:`Checkpointer` API (save/restore/latest_step)
while the real machinery lives in the ``dsml_tpu.checkpoint`` package: a
dependency-free NATIVE backend (sharded binary pieces + JSON manifest,
atomic rename commits, async background writes — ``docs/CHECKPOINT.md``).

Backend selection: native by default. Orbax is OPTIONAL — used only when
explicitly requested (``backend="orbax"`` or ``DSML_CKPT_BACKEND=orbax``)
AND importable; the installed orbax/jax-0.4.37 pairing has known restore
incompatibilities (PyTreeRestore argument drift), which is exactly why the
default moved to the native backend.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from dsml_tpu.utils.logging import get_logger

log = get_logger("checkpoint")


def _pick_backend(backend: str | None) -> str:
    backend = backend or os.environ.get("DSML_CKPT_BACKEND", "") or "native"
    if backend not in ("native", "orbax"):
        raise ValueError(f"unknown checkpoint backend {backend!r} (native | orbax)")
    return backend


class Checkpointer:
    """Training-state persistence: (params, opt_state, epoch/step metadata)
    persist atomically, restore is sharding-aware (arrays come back with
    the template's mesh placement), and async saves never stall the step
    loop. Thin front-end: ``backend="native"`` (default) delegates to
    :class:`dsml_tpu.checkpoint.CheckpointManager`; ``backend="orbax"``
    keeps the original orbax wrapper for environments where it works."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 backend: str | None = None):
        self.backend = _pick_backend(backend)
        self.directory = os.path.abspath(directory)
        if self.backend == "orbax":
            self._impl = _OrbaxCheckpointer(self.directory, max_to_keep)
        else:
            from dsml_tpu.checkpoint import CheckpointManager

            self._impl = _NativeCheckpointer(CheckpointManager(
                self.directory, max_to_keep=max_to_keep))

    def save(self, step: int, params: Any, opt_state: Any = None,
             meta: dict | None = None, wait: bool = True) -> None:
        """Persist training state. ``wait=False`` makes the save ASYNC: the
        device arrays are snapshotted to host before return and written in
        a background thread while training continues — the step loop never
        stalls on disk (call :meth:`wait_until_finished` before shutdown,
        or let the next save's barrier absorb it)."""
        self._impl.save(step, params, opt_state, meta, wait)

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed."""
        self._impl.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._impl.latest_step()

    def restore(self, step: int | None = None, template: Any = None,
                partial: bool = False) -> dict:
        """Restore state. With ``template`` (a pytree of like-shaped arrays,
        e.g. freshly-initialized sharded params), arrays are restored with
        the template's shardings/dtypes — including onto a DIFFERENT mesh
        layout than the save used. ``partial=True`` restores only the
        subtree named by the template (e.g. params without opt_state — the
        inference-load path)."""
        return self._impl.restore(step, template, partial)

    def close(self) -> None:
        self._impl.close()


class _NativeCheckpointer:
    """State-dict adapter: the old API's (params, opt_state, meta) triple
    maps onto one ``{"params": ..., "opt_state": ..., "meta": ...}`` tree."""

    def __init__(self, manager):
        self.manager = manager

    def save(self, step, params, opt_state=None, meta=None, wait=True):
        state = {"params": params}
        if opt_state is not None:
            state["opt_state"] = opt_state
        if meta:
            state["meta"] = dict(meta)
        self.manager.save(step, state, wait=wait)

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def latest_step(self):
        return self.manager.latest_step()

    def restore(self, step=None, template=None, partial=False):
        return self.manager.restore(step, template=template, partial=partial)

    def close(self):
        self.manager.close()


class _OrbaxCheckpointer:
    """The original orbax.checkpoint.CheckpointManager wrapper (explicit
    opt-in only; see module docstring)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step, params, opt_state=None, meta=None, wait=True):
        state = {"params": params}
        if opt_state is not None:
            state["opt_state"] = opt_state
        if meta:
            state["meta"] = dict(meta)
        # PyTreeSave (not StandardSave): the manager binds ONE handler per
        # item name, and only the PyTree handler supports partial restore
        self.manager.save(step, args=self._ocp.args.PyTreeSave(state))
        if wait:
            self.manager.wait_until_finished()
            log.info("saved checkpoint step %d -> %s", step, self.directory)
        else:
            log.info("scheduled async checkpoint save step %d -> %s", step, self.directory)

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def latest_step(self):
        return self.manager.latest_step()

    def restore(self, step=None, template=None, partial=False):
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if template is not None:
            ref = jax.tree.map(self._ocp.utils.to_shape_dtype_struct, template)
            # restore_args carry the template's dtypes AND shardings — plain
            # PyTreeRestore(item=...) would return the dtypes/placements the
            # checkpoint was written with
            restore_args = self._ocp.checkpoint_utils.construct_restore_args(template)
            restored = self.manager.restore(
                step,
                args=self._ocp.args.PyTreeRestore(
                    item=ref, restore_args=restore_args, partial_restore=partial
                ),
            )

            # belt-and-braces: orbax can hand scalar/replicated leaves back
            # on a single device even when the template is mesh-placed —
            # re-place any leaf whose sharding drifted
            def place(t, r):
                if (
                    isinstance(t, jax.Array)
                    and isinstance(r, jax.Array)
                    and r.sharding != t.sharding
                ):
                    return jax.device_put(r, t.sharding)
                return r

            return jax.tree.map(place, template, restored)
        return self.manager.restore(step, args=self._ocp.args.PyTreeRestore())

    def close(self):
        self.manager.close()


def save_arrays(path: str, tree: Any) -> None:
    """Dependency-free fallback: flat .npz of a pytree (used by the wire
    client, which holds plain numpy weights)."""
    flat, treedef = jax.tree.flatten(tree)
    np.savez(path, treedef=str(treedef), **{f"a{i}": np.asarray(x) for i, x in enumerate(flat)})


def load_arrays(path: str, like: Any) -> Any:
    flat, treedef = jax.tree.flatten(like)
    data = np.load(path)
    return jax.tree.unflatten(treedef, [data[f"a{i}"] for i in range(len(flat))])
