"""Checkpoint/resume — Orbax-backed training state persistence.

The reference has NO checkpointing at all (SURVEY.md §5.4: weights live in
client RAM and as opaque device bytes; a crash loses the run). This closes
that capability gap: (params, opt_state, epoch/step metadata) persist
atomically via Orbax, restore is sharding-aware (arrays come back with the
same mesh placement they were saved with when a mesh is supplied), and the
Trainer resumes mid-run.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from dsml_tpu.utils.logging import get_logger

log = get_logger("checkpoint")


class Checkpointer:
    """Thin wrapper over orbax.checkpoint.CheckpointManager."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(
        self,
        step: int,
        params: Any,
        opt_state: Any = None,
        meta: dict | None = None,
        wait: bool = True,
    ) -> None:
        """Persist training state. ``wait=False`` makes the save ASYNC: Orbax
        snapshots the device arrays and writes in a background thread while
        training continues — the step loop never stalls on disk (call
        :meth:`wait_until_finished` before shutdown, or let the next save's
        internal barrier absorb it). The snapshot happens before return, so
        later in-place param updates (donated buffers) can't corrupt it."""
        state = {"params": params}
        if opt_state is not None:
            state["opt_state"] = opt_state
        if meta:
            state["meta"] = dict(meta)
        # PyTreeSave (not StandardSave): the manager binds ONE handler per
        # item name, and only the PyTree handler supports partial restore
        self.manager.save(step, args=self._ocp.args.PyTreeSave(state))
        if wait:
            self.manager.wait_until_finished()
            log.info("saved checkpoint step %d -> %s", step, self.directory)
        else:
            # the background write hasn't committed yet — a "saved" line here
            # would claim a checkpoint that a crash could still lose
            log.info("scheduled async checkpoint save step %d -> %s", step, self.directory)

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed."""
        self.manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore(self, step: int | None = None, template: Any = None, partial: bool = False) -> dict:
        """Restore state. With ``template`` (a pytree of like-shaped arrays,
        e.g. freshly-initialized sharded params), arrays are restored with
        the template's shardings/dtypes. ``partial=True`` restores only the
        subtree named by the template (e.g. params without opt_state — the
        inference-load path)."""
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if template is not None:
            ref = jax.tree.map(self._ocp.utils.to_shape_dtype_struct, template)
            # restore_args carry the template's dtypes AND shardings — plain
            # PyTreeRestore(item=...) would return the dtypes/placements the
            # checkpoint was written with (breaking e.g. a bf16-trained
            # checkpoint loaded into an f32 inference model, or a restore
            # onto a different mesh)
            restore_args = self._ocp.checkpoint_utils.construct_restore_args(template)
            restored = self.manager.restore(
                step,
                args=self._ocp.args.PyTreeRestore(
                    item=ref, restore_args=restore_args, partial_restore=partial
                ),
            )

            # belt-and-braces: Orbax can hand scalar/replicated leaves back
            # on a single device even when the template is mesh-placed —
            # mixing them into a jitted step then fails with "incompatible
            # devices". Re-place any leaf whose sharding drifted.
            def place(t, r):
                if (
                    isinstance(t, jax.Array)
                    and isinstance(r, jax.Array)
                    and r.sharding != t.sharding
                ):
                    return jax.device_put(r, t.sharding)
                return r

            return jax.tree.map(place, template, restored)
        return self.manager.restore(step, args=self._ocp.args.PyTreeRestore())

    def close(self) -> None:
        self.manager.close()


def save_arrays(path: str, tree: Any) -> None:
    """Dependency-free fallback: flat .npz of a pytree (used by the wire
    client, which holds plain numpy weights)."""
    flat, treedef = jax.tree.flatten(tree)
    np.savez(path, treedef=str(treedef), **{f"a{i}": np.asarray(x) for i, x in enumerate(flat)})


def load_arrays(path: str, like: Any) -> Any:
    flat, treedef = jax.tree.flatten(like)
    data = np.load(path)
    return jax.tree.unflatten(treedef, [data[f"a{i}"] for i in range(len(flat))])
