"""Learning-rate schedules — incl. the adaptive scheduler the reference
only documented.

The reference README claims "an adaptive learning rate scheduler"
(``/root/reference/README.md:144``) but ships constant lr=0.01
(``DSML/client/client.go:27``; SURVEY.md §8.8). This module implements the
documented capability for real, plus the standard schedule family used by
the BASELINE.md config ladder (cosine for the transformer runs, step decay
for ResNet/CIFAR — "ring AllReduce + adaptive LR scheduler" is BASELINE
config 4).

Two kinds of objects:

- :func:`make_schedule` → an ``optax.Schedule`` (step → lr), composed into
  any optimizer at build time.
- :func:`adaptive_plateau` → a loss-reactive ``GradientTransformation``
  (optax's reduce-on-plateau) chained AFTER the optimizer; it scales updates
  by a factor that decays when the monitored loss stops improving. This is
  the "adaptive" scheduler the reference promised: it needs the loss value,
  which the train steps thread through via ``optimizer.update(...,
  value=loss)`` (``dsml_tpu.parallel.dp``).
"""

from __future__ import annotations

import optax

__all__ = ["make_schedule", "adaptive_plateau", "wrap_with_plateau"]


def make_schedule(
    name: str,
    base_lr: float,
    total_steps: int,
    warmup_steps: int = 0,
    *,
    step_every: int = 0,
    step_gamma: float = 0.1,
    end_lr_frac: float = 0.0,
):
    """Build an optax schedule by name.

    ``constant | cosine | linear | step`` — all honor ``warmup_steps`` of
    linear warmup from 0. ``step`` decays by ``step_gamma`` every
    ``step_every`` steps (default: thirds of the run, the classic
    ResNet/CIFAR staircase).
    """
    total_steps = max(total_steps, 1)
    warmup_steps = min(max(warmup_steps, 0), total_steps - 1)  # leave ≥1 decay step
    if name in ("constant", "plateau"):  # plateau = constant base + reactive scale
        body = optax.constant_schedule(base_lr)
    elif name == "cosine":
        # optax needs warmup ≥ 1 AND decay span > warmup; a 1-step run would
        # otherwise produce decay_steps = 0
        warmup = max(warmup_steps, 1)
        return optax.warmup_cosine_decay_schedule(
            0.0, base_lr, warmup, max(total_steps, warmup + 1), end_value=base_lr * end_lr_frac
        )
    elif name == "linear":
        body = optax.linear_schedule(base_lr, base_lr * end_lr_frac, total_steps - warmup_steps)
    elif name == "step":
        every = step_every or max(total_steps // 3, 1)
        boundaries = {i: step_gamma for i in range(every, total_steps, every)}
        body = optax.piecewise_constant_schedule(base_lr, boundaries)
    else:
        raise ValueError(f"unknown lr schedule {name!r}")
    if warmup_steps > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, base_lr, warmup_steps), body], [warmup_steps]
        )
    return body


def adaptive_plateau(
    factor: float = 0.5,
    patience: int = 5,
    rtol: float = 1e-4,
    cooldown: int = 0,
    accumulation_size: int = 1,
    min_scale: float = 1e-3,
) -> optax.GradientTransformation:
    """Reduce-on-plateau transform: multiplies updates by a running scale
    that shrinks by ``factor`` after ``patience`` non-improving loss values.
    Chain after an optimizer; requires ``update(..., value=loss)``."""
    return optax.contrib.reduce_on_plateau(
        factor=factor,
        patience=patience,
        rtol=rtol,
        cooldown=cooldown,
        accumulation_size=accumulation_size,
        min_scale=min_scale,
    )


def wrap_with_plateau(optimizer: optax.GradientTransformation, **kwargs) -> optax.GradientTransformation:
    """``optimizer`` then :func:`adaptive_plateau`, as one transformation."""
    return optax.chain(optimizer, adaptive_plateau(**kwargs))
