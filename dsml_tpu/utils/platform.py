"""Platform selection for CLI processes.

The container pins ``JAX_PLATFORMS`` at interpreter start (sitecustomize), so
env vars alone can't retarget a process; this goes through ``jax.config``
before any backend initializes.
"""

from __future__ import annotations


def configure_platform(platform: str = "", cpu_devices: int = 0) -> None:
    """Set the jax platform ("cpu"/"tpu"/"" = container default) and, for
    CPU, the virtual device count (0 = leave as-is)."""
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if cpu_devices:
        jax.config.update("jax_num_cpu_devices", cpu_devices)
