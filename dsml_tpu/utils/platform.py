"""Platform selection for CLI processes.

The container pins ``JAX_PLATFORMS`` at interpreter start (sitecustomize), so
env vars alone can't retarget a process; this goes through ``jax.config``
before any backend initializes.
"""

from __future__ import annotations


def configure_platform(
    platform: str = "", cpu_devices: int = 0, cpu_collectives: str = ""
) -> None:
    """Set the jax platform ("cpu"/"tpu"/"" = container default), the CPU
    virtual device count (0 = leave as-is), and the CPU cross-process
    collectives backend ("gloo" for multi-process CPU clusters — required
    before :func:`init_distributed` on CPU)."""
    import os

    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if cpu_devices:
        try:
            jax.config.update("jax_num_cpu_devices", cpu_devices)
        except AttributeError:
            # jax < 0.5: the device count comes from XLA_FLAGS, read at
            # backend init — effective only if no backend has initialized
            # yet (same caveat the config option carries on new jax)
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={cpu_devices}"
                ).strip()
    if cpu_collectives:
        jax.config.update("jax_cpu_collectives_implementation", cpu_collectives)


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join a multi-host JAX cluster (DCN scale-out) and return this
    process's index.

    The mesh/collective layers are host-count-agnostic: ``jax.devices()``
    spans every host after this call, so the same ``build_mesh`` +
    ``shard_map`` programs run across pods — DCN traffic is inserted by XLA
    where mesh axes cross hosts (SURVEY.md §5.8's "TPU-native equivalent").
    On TPU pods all three arguments auto-detect from the environment; pass
    them explicitly elsewhere (e.g. CPU clusters for tests).

    No-op (returns 0) when num_processes == 1 or JAX was already
    initialized for this cluster.
    """
    import jax

    if num_processes == 1:
        return 0
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:  # double-init → idempotent no-op
        # jax 0.9 phrases this "distributed.initialize should only be called
        # once."; older versions said "already initialized"
        msg = str(e).lower()
        if "once" not in msg and "already" not in msg:
            raise
    return jax.process_index()
