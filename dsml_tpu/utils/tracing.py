"""Tracing/profiling: XLA profiler hooks + collective latency measurement.

The reference's entire observability story is wall-clock ``time.Now()``
pairs around the naive all-reduce (SURVEY.md §5.1). Here:

- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable XLA trace (per-op device timelines, fusion view).
  Capture failures on the pinned jax 0.4.37 raise
  :class:`~dsml_tpu.obs.ObsUnavailable` with remediation text instead of
  an opaque backend traceback.
- :func:`time_jitted` — p50/p90 wall latency of an already-jitted callable
  with proper warmup + ``block_until_ready`` fencing. Samples feed the
  observability registry (``time_jitted_ms`` histogram) when it is
  enabled.
- :func:`ring_latency_ms` — the BASELINE.md headline: p50 latency of the
  2(n-1)-step ring all-reduce at a given payload size, timed as ONE device
  program (no host staging in the loop). Samples feed
  ``collective_latency_ms{algorithm=...}`` — the same per-algorithm
  accounting surface ``bench.py --section obs`` populates.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable

import numpy as np

from dsml_tpu.obs import ObsUnavailable, get_registry, observe_collective_latency_ms
from dsml_tpu.utils.logging import get_logger

log = get_logger("tracing")


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace into ``log_dir``.

    The pinned jax 0.4.37 can fail the capture in several environment-
    dependent ways (no profiler backend linked into the CPU wheel, a
    second concurrent capture, a dead TPU tunnel mid-stop); each surfaces
    as :class:`ObsUnavailable` naming the fix instead of a raw backend
    stack."""
    import jax

    def _unavailable(stage: str, e: Exception) -> ObsUnavailable:
        return ObsUnavailable(
            f"jax.profiler trace {stage} failed on this jax build "
            f"({jax.__version__}): {e!r}. Remediation: ensure no other "
            "capture is active, that the backend links a profiler "
            "(CPU wheels may not), and that the device is reachable; for "
            "host-side timing that always works, use dsml_tpu.obs.span "
            "(Chrome trace-event export) instead."
        )

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:  # noqa: BLE001 — backend-dependent failure set
        raise _unavailable("start", e) from e
    body_failed = False
    try:
        yield log_dir
    except BaseException:
        body_failed = True
        raise
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            if body_failed:
                # the body's exception is already propagating — a raise here
                # would REPLACE it with the (secondary) capture failure
                log.warning("profiler stop_trace failed during unwind: %r", e)
            else:
                raise _unavailable("stop", e) from e
        else:
            log.info("profiler trace written to %s", log_dir)


def time_jitted(fn: Callable, *args, iters: int = 20, warmup: int = 3) -> dict:
    """Latency stats (ms) of ``fn(*args)``; the result must be a jax array
    (or pytree with one leaf to fence on)."""
    import jax

    def fence(out):
        jax.tree.leaves(out)[0].block_until_ready()

    for _ in range(warmup):
        fence(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fence(fn(*args))
        samples.append((time.perf_counter() - t0) * 1000.0)
    arr = np.asarray(samples)
    reg = get_registry()
    if reg.enabled:
        hist = reg.histogram("time_jitted_ms", "time_jitted wall samples")
        for ms in samples:
            hist.observe(ms)
    return {
        "p50_ms": float(np.percentile(arr, 50)),
        "p90_ms": float(np.percentile(arr, 90)),
        "mean_ms": float(arr.mean()),
        "iters": iters,
        "samples_ms": [round(s, 6) for s in samples],
    }


def ring_latency_ms(mesh, payload_bytes: int = 1 << 20, algorithm: str = "ring") -> dict:
    """p50 latency of an all-reduce of ``payload_bytes`` per device over
    ``mesh`` (default 1 MB — the reference's benchmark payload, which it
    'reduced' in 8 ms of simulated loopback; this number is a real
    collective). The buffers stay on device; only the timing fence touches
    the host."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dsml_tpu.ops.collectives import ReduceOp, all_reduce

    axis = mesh.axis_names[0] if len(mesh.axis_names) == 1 else "dp"
    n = mesh.shape[axis]
    elems = payload_bytes // 4

    spec = P(axis)
    fn = jax.jit(
        jax.shard_map(
            lambda x: all_reduce(x[0], axis, ReduceOp.SUM, algorithm)[None],
            mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
        ),
        in_shardings=NamedSharding(mesh, spec),
        out_shardings=NamedSharding(mesh, spec),
    )
    x = jax.device_put(
        jnp.ones((n, elems), jnp.float32), NamedSharding(mesh, spec)
    )
    stats = time_jitted(fn, x)
    for ms in stats.pop("samples_ms"):
        observe_collective_latency_ms(
            algorithm, ms, payload_bytes=payload_bytes, axis=axis
        )
    stats.update(payload_bytes=payload_bytes, devices=n, algorithm=algorithm)
    return stats
