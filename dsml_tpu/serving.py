"""Continuous-batching serving — slot-based decode with in-flight admission.

The reference has no inference path at all (SURVEY.md §5; its client only
trains, ``client.go:516-659``); the framework's serving stack already does
static batched decode (``GPT2.generate``/``generate_spmd``). This module
adds the throughput layer a real serving deployment needs: requests arrive
at different times with different prompt/output lengths, and a static
batch would idle every slot until the LONGEST request finishes. Continuous
batching (the vLLM/Orca scheduling idea) retires each request the moment
it completes and admits a queued one into the freed slot — realized here
TPU-first:

- ONE jitted decode program for all slots (``model.decode_step_slots``):
  fully static shapes, per-slot depths carried as a ``pos`` vector, cache
  writes as a batched scatter, attention masked to ``s <= pos[b]`` per
  row. No recompilation ever happens at steady state.
- Prefill compiles once per PROMPT BUCKET (next power-of-two length):
  prompts are right-padded to the bucket, the logits read at the true
  last index (``prefill(last_index=L-1)``), and the new request's cache
  rows are scattered into its slot.
- The host-side scheduler is a plain loop: admit → decode → emit/retire.
  Sampling is greedy or temperature-based with a per-request key, so a
  request's tokens are independent of which slot/step served it.

Single-device by design (the TP/DP-sharded decode lives in
``generate_spmd``); slots × continuous admission is the axis this module
adds.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Request", "ContinuousBatcher"]


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray  # [L] int32
    max_new_tokens: int
    tokens: list = dataclasses.field(default_factory=list)  # emitted so far
    done: bool = False


def _bucket(n: int, buckets: tuple) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest bucket {buckets[-1]}")


class ContinuousBatcher:
    """Slot-based continuous-batching decoder over one model + params.

    ``submit`` enqueues prompts; ``step`` admits queued requests into free
    slots (bucketed prefill), runs ONE slot-decode step, emits new tokens,
    and retires finished requests (EOS or token budget). ``run`` drains
    everything. Greedy by default; ``temperature > 0`` samples with a
    per-request fold of ``seed`` so results don't depend on slot timing.
    """

    def __init__(
        self,
        model,
        params,
        n_slots: int = 8,
        eos_id: int | None = None,
        temperature: float = 0.0,
        seed: int = 0,
        prompt_buckets: tuple = (32, 64, 128, 256, 512, 1024),
    ):
        cfg = model.config
        self.model = model
        self.params = params
        self.n_slots = n_slots
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.seed = seed
        self.prompt_buckets = tuple(b for b in prompt_buckets if b <= cfg.max_seq)
        if not self.prompt_buckets:
            raise ValueError(f"no prompt bucket fits max_seq={cfg.max_seq}")

        self._queue: deque[Request] = deque()
        self._live: dict[int, Request] = {}  # queued or in a slot
        self._done: dict[int, Request] = {}  # retired, awaiting collect()
        self._next_rid = 0
        # slot state (host-side numpy; device state is the cache)
        self._slot_rid = np.full(n_slots, -1, np.int64)  # -1 = free
        self._pos = np.zeros(n_slots, np.int32)  # next cache write index
        self._last_tok = np.zeros(n_slots, np.int32)
        self._cache = model.init_cache(n_slots)

        # the cache is donated: XLA updates it in place each step instead of
        # allocating + copying the full [slots, H, max_seq, hd] buffers per
        # token (params are NOT donated — they serve every step)
        self._decode = jax.jit(
            lambda p, c, t, pos: model.decode_step_slots(p, c, t, pos),
            donate_argnums=(1,),
        )
        # one prefill compile per bucket length (static last_index would
        # recompile per prompt length — keep it traced)
        self._prefill = jax.jit(
            lambda p, toks, last: model.prefill(p, toks, last_index=last)
        )
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0,))

    @staticmethod
    def _insert_fn(cache, cache1, slot):
        """Scatter a 1-row prefill cache into slot ``slot`` of the big
        cache (the admission write)."""
        return [
            {
                "k": c["k"].at[slot].set(c1["k"][0]),
                "v": c["v"].at[slot].set(c1["v"][0]),
            }
            for c, c1 in zip(cache, cache1)
        ]

    # ---- request interface -----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cfg = self.model.config
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq={cfg.max_seq}"
            )
        _bucket(len(prompt), self.prompt_buckets)  # reject at submit, not admit
        if max_new_tokens < 1:
            # generate raises for this too — the serving path must not
            # silently emit a token for a zero-budget request
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens)
        self._queue.append(req)
        self._live[rid] = req
        return rid

    @property
    def n_active(self) -> int:
        return int((self._slot_rid >= 0).sum())

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    # ---- scheduling ------------------------------------------------------------

    def _sample(self, logits: np.ndarray, req: Request) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits))
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), req.rid)
        key = jax.random.fold_in(key, len(req.tokens))
        scaled = jnp.asarray(logits, jnp.float32) / self.temperature
        return int(jax.random.categorical(key, scaled))

    def _admit(self) -> None:
        """Fill free slots from the queue: bucketed prefill + cache insert +
        first sampled token. A request that finishes AT prefill (budget 1 or
        immediate EOS) never occupies the slot, so the same slot admits the
        next queued request within this pass."""
        for slot in np.flatnonzero(self._slot_rid < 0):
            while self._queue and self._slot_rid[slot] < 0:
                req = self._queue.popleft()
                L = len(req.prompt)
                bucket = _bucket(L, self.prompt_buckets)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :L] = req.prompt
                logits, cache1 = self._prefill(
                    self.params, jnp.asarray(padded), jnp.int32(L - 1)
                )
                self._cache = self._insert(self._cache, cache1, int(slot))
                tok = self._sample(np.asarray(logits[0]), req)
                req.tokens.append(tok)
                if self._finished(req, tok):
                    self._retire(req)  # slot still free: while-loop admits next
                    continue
                self._slot_rid[slot] = req.rid
                self._pos[slot] = L
                self._last_tok[slot] = tok

    def _finished(self, req: Request, tok: int) -> bool:
        return (self.eos_id is not None and tok == self.eos_id) or (
            len(req.tokens) >= req.max_new_tokens
        )

    def _retire(self, req: Request) -> None:
        req.done = True
        # move out of the live table so a long-running server doesn't
        # accumulate one Request per lifetime request; collect() drains
        self._done[req.rid] = self._live.pop(req.rid)

    def step(self) -> dict[int, int]:
        """One scheduler tick: admit, one decode step over ALL slots, emit.
        Returns {rid: new token} for every active request this tick."""
        self._admit()
        active = np.flatnonzero(self._slot_rid >= 0)
        if len(active) == 0:
            return {}
        logits, self._cache = self._decode(
            self.params,
            self._cache,
            jnp.asarray(self._last_tok),
            jnp.asarray(self._pos),
        )
        logits = np.asarray(logits)
        emitted: dict[int, int] = {}
        for slot in active:
            req = self._live[int(self._slot_rid[slot])]
            tok = self._sample(logits[slot], req)
            req.tokens.append(tok)
            emitted[req.rid] = tok
            self._pos[slot] += 1
            self._last_tok[slot] = tok
            if self._finished(req, tok):
                self._retire(req)
                self._slot_rid[slot] = -1  # slot freed → next admit reuses it
        return emitted

    def collect(self) -> dict[int, list]:
        """{rid: [tokens]} for every request retired since the last collect
        (drained — repeated calls don't re-report, and the batcher holds no
        per-request state afterwards)."""
        done = {rid: req.tokens for rid, req in self._done.items()}
        self._done.clear()
        return done

    def run(self, max_steps: int = 100_000) -> dict[int, list]:
        """Drain queue + slots; returns {rid: [tokens]} for every request
        retired during (or before) this call."""
        for _ in range(max_steps):
            if not self._queue and self.n_active == 0:
                break
            self.step()
        else:
            raise RuntimeError(f"serving did not drain within {max_steps} steps")
        return self.collect()
